"""Serial (single-device) leaf-wise tree learner.

TPU re-design of the reference SerialTreeLearner + GPUTreeLearner
(/root/reference/src/treelearner/serial_tree_learner.cpp:168-574,
gpu_tree_learner.cpp): the leaf-wise policy, smaller/larger-child
subtraction trick (serial_tree_learner.cpp:344-422) and gain math are kept;
the mechanisms are replaced:

- DataPartition's index shuffling (data_partition.hpp:94-146) becomes a
  per-row `leaf_id` vector updated by a masked predicate — no data movement.
- Row sets for histogramming are compacted with `jnp.nonzero(size=cap)`
  where `cap` is the leaf count rounded up to a power of two.  Each cap is
  a separate jit specialization — the analog of the reference GPU learner
  compiling kernels for 11 workgroup powers (gpu_tree_learner.cpp:557-626):
  ~log2(N) variants total, cached across trees and iterations.
- Histograms run as one-hot matmuls on the MXU (ops/histogram.py); best
  splits as [F, B] cumsum scans (ops/split.py).

The split loop itself stays on the host (like the reference), but each step
is a single fused device program + one small device->host transfer of the
two children's packed split records.
"""
from __future__ import annotations

import functools
import math
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..config import Config
from ..dataset import Dataset
from .common import make_split_kw, padded_bin_count, sentinel_bins_t
from ..ops.histogram import histogram_from_indices
from ..ops.split import (best_split, bundle_predicate_params,
                         identity_feat_table, maybe_unbundle, store_go_left,
                         SplitResult)
from ..tree import Tree, NUMERICAL_DECISION, CATEGORICAL_DECISION
from ..binning import CATEGORICAL


def _next_pow2(n: int) -> int:
    return 1 << max(int(n) - 1, 0).bit_length() if n > 1 else 1


@functools.partial(jax.jit, static_argnames=("cap", "num_bins_padded",
                                             "backend", "split_kw"))
def _root_step(bins_t, grad_pad, hess_pad, idx, num_bins, is_cat, fmask,
               unb, *, cap, num_bins_padded, backend, split_kw):
    hist = histogram_from_indices(bins_t, grad_pad, hess_pad, idx,
                                  num_bins_padded=num_bins_padded,
                                  backend=backend)
    sum_g = jnp.sum(hist[0, 0, :])
    sum_h = jnp.sum(hist[0, 1, :])
    cnt = jnp.sum(hist[0, 2, :])
    sums = jnp.stack([sum_g, sum_h, cnt])
    h = maybe_unbundle(hist, unb, sums)
    rec = best_split(h, num_bins, is_cat, fmask, sum_g, sum_h, cnt,
                     **dict(split_kw))
    return hist, rec.packed(), sums


def _store_partition(bins, leaf_id, parent_leaf, new_leaf, feat, thr,
                     is_cat_split, ftbl):
    """Move the parent's right-going rows to new_leaf, evaluating the
    ORIGINAL-space split (feat, thr) on the bundled store via the
    store-space predicate (ops/split.bundle_predicate_params)."""
    N = leaf_id.shape[0]
    col, T, lo, hi1, dl = bundle_predicate_params(ftbl, feat, thr,
                                                  is_cat_split)
    featrow = jax.lax.dynamic_index_in_dim(bins, col, axis=0,
                                           keepdims=False)[:N]
    featrow = featrow.astype(jnp.int32)
    pred = store_go_left(featrow, T, lo, hi1, dl, is_cat_split)
    in_parent = leaf_id == parent_leaf
    return jnp.where(in_parent & ~pred, new_leaf, leaf_id)


@functools.partial(jax.jit, static_argnames=("cap", "num_bins_padded",
                                             "backend", "split_kw",
                                             "with_subtract"))
def _split_step(bins, bins_t, grad_pad, hess_pad, leaf_id, parent_leaf,
                new_leaf, feat, thr, is_cat_split, smaller_leaf, parent_hist,
                num_bins, is_cat, fmask, small_sums, large_sums, ftbl, unb,
                *, cap, num_bins_padded, backend, split_kw, with_subtract):
    """Partition parent rows, histogram the smaller child (gathered, cap
    static), obtain the larger by subtraction, best-split both.  The
    cached/returned histograms stay in STORE space; split search runs on
    the unbundled per-feature view."""
    N = leaf_id.shape[0]
    leaf_id = _store_partition(bins, leaf_id, parent_leaf, new_leaf, feat,
                               thr, is_cat_split, ftbl)

    small_mask = leaf_id == smaller_leaf
    idx = jnp.nonzero(small_mask, size=cap, fill_value=N)[0].astype(jnp.int32)
    hist_small = histogram_from_indices(bins_t, grad_pad, hess_pad, idx,
                                        num_bins_padded=num_bins_padded,
                                        backend=backend)
    if with_subtract:
        hist_large = parent_hist - hist_small
    else:
        hist_large = parent_hist  # unused placeholder
    kw = dict(split_kw)
    rec_small = best_split(maybe_unbundle(hist_small, unb, small_sums),
                           num_bins, is_cat, fmask,
                           small_sums[0], small_sums[1], small_sums[2], **kw)
    rec_large = best_split(maybe_unbundle(hist_large, unb, large_sums),
                           num_bins, is_cat, fmask,
                           large_sums[0], large_sums[1], large_sums[2], **kw)
    return (leaf_id, hist_small, hist_large,
            jnp.stack([rec_small.packed(), rec_large.packed()]))


@jax.jit
def _partition_only(bins, leaf_id, parent_leaf, new_leaf, feat, thr,
                    is_cat_split, ftbl):
    return _store_partition(bins, leaf_id, parent_leaf, new_leaf, feat,
                            thr, is_cat_split, ftbl)


class _LeafInfo:
    __slots__ = ("sum_grad", "sum_hess", "count", "depth", "hist", "best")

    def __init__(self, sum_grad, sum_hess, count, depth, hist, best):
        self.sum_grad = sum_grad
        self.sum_hess = sum_hess
        self.count = count
        self.depth = depth
        self.hist = hist      # device [F, 3, B] or None
        self.best = best      # numpy packed record or None


class SerialTreeLearner:
    def __init__(self, dataset: Dataset, config: Config):
        self.dataset = dataset
        self.config = config
        self.N = dataset.num_data
        self.F = dataset.num_features              # ORIGINAL feature count
        # bin axis sized by the STORE (bundled columns hold >= any
        # member's bins, so one padded count serves histogram and the
        # unbundled split search alike)
        self.B = padded_bin_count(dataset.max_num_bin)
        bt = sentinel_bins_t(dataset)              # store layout [N+1, C]
        self.bins = jnp.asarray(bt.T.copy())   # [C, N+1]
        self.bins_t = jnp.asarray(bt)          # [N+1, C]
        self.num_bins_dev = jnp.asarray(dataset.num_bins)
        self.is_cat_dev = jnp.asarray(dataset.is_categorical)
        ft = dataset.bundle_feat_table()
        self.ftbl = (identity_feat_table(dataset.num_bins) if ft is None
                     else jnp.asarray(ft))
        unb = dataset.unbundle_tables(self.B)
        self.unb = (None if unb is None
                    else (jnp.asarray(unb[0]), jnp.asarray(unb[1])))
        self.backend = ("pallas" if config.device_type == "tpu" and
                        jax.default_backend() == "tpu" else "xla")
        cfg = config
        self.split_kw = make_split_kw(cfg)
        self._feat_rng = np.random.RandomState(cfg.feature_fraction_seed)
        # memory guard: keep per-leaf histograms only if the full set fits
        # (cached histograms live in STORE space — bundling shrinks them)
        hist_bytes = dataset.num_store_columns * 3 * self.B * 4
        pool_budget = (cfg.histogram_pool_size * 1e6
                       if cfg.histogram_pool_size > 0 else 1.5e9)
        self.keep_hists = hist_bytes * cfg.num_leaves <= pool_budget
        self.leaf_id: Optional[jax.Array] = None

    # -- helpers -----------------------------------------------------------

    def _feature_mask(self) -> jax.Array:
        frac = self.config.feature_fraction
        if frac >= 1.0:
            return jnp.ones(self.F, dtype=bool)
        k = max(1, int(round(self.F * frac)))
        sel = self._feat_rng.choice(self.F, size=k, replace=False)
        m = np.zeros(self.F, dtype=bool)
        m[sel] = True
        return jnp.asarray(m)

    def _cap(self, count: int) -> int:
        return min(_next_pow2(max(int(count), 1)), self.N)

    def _can_split(self, info: _LeafInfo) -> bool:
        cfg = self.config
        if info.count < 2 * cfg.min_data_in_leaf:
            return False
        if info.sum_hess < 2 * cfg.min_sum_hessian_in_leaf:
            return False
        if cfg.max_depth > 0 and info.depth >= cfg.max_depth:
            return False
        return True

    def _direct_hist_best(self, leaf: int, info: _LeafInfo):
        """Histogram a leaf directly (no subtraction) — root and pool-miss
        path (reference HistogramPool miss → recompute)."""
        cap = self._cap(info.count)
        idx = jnp.nonzero(self.leaf_id == leaf, size=cap,
                          fill_value=self.N)[0].astype(jnp.int32)
        hist, packed, sums = _root_step(
            self.bins_t, self._grad_pad, self._hess_pad, idx,
            self.num_bins_dev, self.is_cat_dev, self._fmask, self.unb,
            cap=cap, num_bins_padded=self.B, backend=self.backend,
            split_kw=self.split_kw)
        return hist, np.asarray(packed)

    # -- main --------------------------------------------------------------

    def train(self, grad: jax.Array, hess: jax.Array,
              bag_idx: Optional[jax.Array] = None,
              bag_count: Optional[int] = None) -> Tuple[Tree, jax.Array]:
        """Grow one tree.  grad/hess: [N] f32 device arrays.

        Returns (tree, leaf_id) where leaf_id[i] is the leaf index of row i
        (-1 for out-of-bag rows) — used for the fast train-score update
        (reference serial_tree_learner.h:52-64 AddPredictionToScore).
        """
        cfg = self.config
        N = self.N
        zero = jnp.zeros((1,), grad.dtype)
        self._grad_pad = jnp.concatenate([grad, zero])
        self._hess_pad = jnp.concatenate([hess, zero])
        self._fmask = self._feature_mask()

        if bag_idx is None:
            self.leaf_id = jnp.zeros(N, jnp.int32)
            root_count = N
            idx = jnp.arange(N, dtype=jnp.int32)
        else:
            root_count = int(bag_count)
            # out-of-bag rows get leaf -1; the sentinel pad index N in
            # bag_idx is out of bounds and dropped by the scatter
            self.leaf_id = jnp.full(N, -1, jnp.int32).at[bag_idx].set(0)
            idx = bag_idx.astype(jnp.int32)

        hist, packed, sums = _root_step(
            self.bins_t, self._grad_pad, self._hess_pad, idx,
            self.num_bins_dev, self.is_cat_dev, self._fmask, self.unb,
            cap=int(idx.shape[0]), num_bins_padded=self.B,
            backend=self.backend, split_kw=self.split_kw)
        sums = np.asarray(sums, dtype=np.float64)

        tree = Tree(cfg.num_leaves)
        leaves: Dict[int, _LeafInfo] = {
            0: _LeafInfo(sums[0], sums[1], root_count, 0, hist,
                         np.asarray(packed))}

        for _ in range(cfg.num_leaves - 1):
            # pick best leaf (global greedy, serial_tree_learner.cpp:203-210)
            best_leaf, best_gain = -1, 0.0
            for lf, info in leaves.items():
                if info.best is None:
                    continue
                g = float(info.best[0])
                if np.isfinite(g) and g > best_gain:
                    best_leaf, best_gain = lf, g
            if best_leaf < 0:
                break
            info = leaves[best_leaf]
            rec = info.best
            feat = int(rec[1]); thr = int(rec[2])
            l_sum = (float(rec[3]), float(rec[4]), int(round(float(rec[5]))))
            r_sum = (float(rec[6]), float(rec[7]), int(round(float(rec[8]))))
            l_out, r_out = float(rec[9]), float(rec[10])
            real_feat = self.dataset.inner_to_real(feat)
            mapper = self.dataset.mappers[real_feat]
            bin_type = (CATEGORICAL_DECISION
                        if mapper.bin_type == CATEGORICAL else NUMERICAL_DECISION)
            new_leaf = tree.split(
                best_leaf, feat, bin_type, thr, real_feat,
                mapper.bin_to_value(thr), l_out, r_out, l_sum[2], r_sum[2],
                best_gain)

            child_depth = info.depth + 1
            left = _LeafInfo(l_sum[0], l_sum[1], l_sum[2], child_depth,
                             None, None)
            right = _LeafInfo(r_sum[0], r_sum[1], r_sum[2], child_depth,
                              None, None)
            need_l, need_r = self._can_split(left), self._can_split(right)
            is_cat_split = jnp.asarray(bin_type == CATEGORICAL_DECISION)

            if need_l or need_r:
                # smaller child is histogrammed; larger by subtraction
                # (serial_tree_learner.cpp:344-422 smaller/larger trick)
                small_is_left = l_sum[2] <= r_sum[2]
                small_leaf = best_leaf if small_is_left else new_leaf
                small = left if small_is_left else right
                large = right if small_is_left else left
                need_small = need_l if small_is_left else need_r
                need_large = need_r if small_is_left else need_l
                cap = self._cap(small.count)
                with_subtract = info.hist is not None
                parent_hist = (info.hist if with_subtract else
                               jnp.zeros((self.dataset.num_store_columns,
                                          3, self.B), jnp.float32))
                (self.leaf_id, hist_small, hist_large, recs) = _split_step(
                    self.bins, self.bins_t, self._grad_pad, self._hess_pad,
                    self.leaf_id, best_leaf, new_leaf, feat, thr,
                    is_cat_split, small_leaf, parent_hist,
                    self.num_bins_dev, self.is_cat_dev, self._fmask,
                    jnp.asarray([small.sum_grad, small.sum_hess,
                                 float(small.count)], jnp.float32),
                    jnp.asarray([large.sum_grad, large.sum_hess,
                                 float(large.count)], jnp.float32),
                    self.ftbl, self.unb,
                    cap=cap, num_bins_padded=self.B, backend=self.backend,
                    split_kw=self.split_kw, with_subtract=with_subtract)
                recs = np.asarray(recs)
                if need_small:
                    small.hist, small.best = hist_small, recs[0]
                if need_large:
                    if with_subtract:
                        large.hist, large.best = hist_large, recs[1]
                    else:
                        # pool-dropped parent (HistogramPool miss analog):
                        # recompute the larger child directly
                        lg_leaf = new_leaf if small_is_left else best_leaf
                        large.hist, large.best = self._direct_hist_best(
                            lg_leaf, large)
                if not self.keep_hists:
                    small.hist = None
                    large.hist = None
            else:
                self.leaf_id = _partition_only(
                    self.bins, self.leaf_id, best_leaf, new_leaf, feat, thr,
                    is_cat_split, self.ftbl)

            leaves[best_leaf] = left
            leaves[new_leaf] = right
            info.hist = None

        return tree, self.leaf_id
