"""Setup shared by the serial and fused tree learners — kept in one place
so the two learners (which must grow identical trees,
tests/test_parallel.py) cannot silently diverge."""
from __future__ import annotations

import math

import numpy as np

from ..config import Config


def make_split_kw(cfg: Config) -> tuple:
    """Hashable (static-arg) split hyperparameters for ops.split.best_split
    (reference feature_histogram.hpp:281-300 gain math inputs)."""
    return tuple(sorted(dict(
        lambda_l1=float(cfg.lambda_l1), lambda_l2=float(cfg.lambda_l2),
        min_data_in_leaf=int(cfg.min_data_in_leaf),
        min_sum_hessian_in_leaf=float(cfg.min_sum_hessian_in_leaf),
        min_gain_to_split=float(cfg.min_gain_to_split)).items()))


def padded_bin_count(max_num_bin: int) -> int:
    """Bin axis padded to a lane-friendly multiple of 128."""
    return max(128, int(128 * math.ceil(max_num_bin / 128)))


def sentinel_bins_t(dataset) -> np.ndarray:
    """[N+1, F] int32 transpose with a sentinel row at index N (bin 0) so
    padded gathers are branch-free."""
    bins_np = dataset.bins.astype(np.int32)
    pad = np.zeros((dataset.num_features, 1), np.int32)
    return np.concatenate([bins_np, pad], axis=1).T.copy()


def _default_pool_budget() -> float:
    """Unset histogram_pool_size defaults to a quarter of the device's
    memory when the backend reports it (16 GB v5e -> 4 GB: Epsilon-scale
    [255, 2000, 3, 256] caches fit and keep the 2x-cheaper subtraction
    path), else a conservative 1.5 GB."""
    try:
        import jax
        stats = jax.devices()[0].memory_stats()
        if stats and stats.get("bytes_limit"):
            return max(1.5e9, 0.25 * float(stats["bytes_limit"]))
    except Exception:
        pass
    return 1.5e9


def use_parent_hist_cache(cfg: Config, num_features: int,
                          num_bins_padded: int) -> bool:
    """Keep the [num_leaves, F, 3, B] per-leaf histogram cache for the
    parent-subtraction trick only while it fits the pool budget
    (reference HistogramPool cap, feature_histogram.hpp:313-475);
    otherwise learners histogram both children directly."""
    hist_cache_bytes = 4 * cfg.num_leaves * num_features * 3 * num_bins_padded
    budget = (cfg.histogram_pool_size * 1e6
              if cfg.histogram_pool_size > 0 else _default_pool_budget())
    return hist_cache_bytes <= budget
