"""Setup shared by the serial and fused tree learners — kept in one place
so the two learners (which must grow identical trees,
tests/test_parallel.py) cannot silently diverge."""
from __future__ import annotations

import math

import numpy as np

from ..config import Config


class MultiHostRows:
    """Row-block layout + assembly for multi-process data-parallel
    training: the mesh "data" axis spans processes, each process owns one
    contiguous row block (the loader's pre-partition contract,
    dataset.py pre_partition; reference dataset_loader.cpp:554-659).

    Every process pads its block to the same per-process length so the
    global [Np] row axis tiles evenly over the axis devices; global
    arrays are assembled with `jax.make_array_from_process_local_data`
    (the multi-controller analog of the reference's implicit "my rows
    are mine" layout — no data ever crosses hosts, only collectives).
    """

    def __init__(self, mesh, n_local: int):
        import jax
        from jax.experimental import multihost_utils
        axes = dict(zip(mesh.axis_names, mesh.devices.shape))
        dd = int(axes.get("data", 1))
        self.world = jax.process_count()
        if dd % self.world:
            raise ValueError(
                f"data axis ({dd}) must be divisible by the process count "
                f"({self.world}) for multi-host training")
        if int(axes.get("feature", 1)) > 1:
            raise NotImplementedError(
                "multi-host feature-parallel training is not supported; "
                "use tree_learner=data")
        self.local_dd = dd // self.world
        ns = np.asarray(multihost_utils.process_allgather(
            np.asarray([n_local], np.int64))).reshape(-1)
        self.n_local = int(n_local)
        per = int(ns.max())
        self.per_proc = self.local_dd * int(math.ceil(
            per / self.local_dd)) if per else self.local_dd
        self.np_global = self.per_proc * self.world
        self.n_global = int(ns.sum())
        self.mesh = mesh

    def pad_local(self, x: np.ndarray) -> np.ndarray:
        """Zero-pad the last (row) axis of a LOCAL block to per_proc."""
        pad = self.per_proc - x.shape[-1]
        if pad == 0:
            return x
        widths = [(0, 0)] * (x.ndim - 1) + [(0, pad)]
        return np.pad(x, widths)

    def put_rows(self, x_local: np.ndarray, spec):
        """Assemble the global row-sharded array from this process's
        padded local block (shape [..., per_proc])."""
        import jax
        from jax.sharding import NamedSharding
        return jax.make_array_from_process_local_data(
            NamedSharding(self.mesh, spec), np.ascontiguousarray(x_local))

    def local_rows(self, arr) -> np.ndarray:
        """Extract this process's rows from a global row-sharded array
        (last axis = rows), trimmed back to the unpadded local length."""
        shards = sorted(
            ((s.index[-1].start or 0, np.asarray(s.data))
             for s in arr.addressable_shards), key=lambda t: t[0])
        return np.concatenate([d for _, d in shards],
                              axis=-1)[..., : self.n_local]


def compat_shard_map(f, *, mesh, in_specs, out_specs, check_vma=False):
    """jax.shard_map with a fallback to the pre-graduation API
    (jax<=0.5 ships it as jax.experimental.shard_map.shard_map, with
    the replication-check flag named check_rep instead of check_vma)."""
    import jax
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map
    return shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                     check_rep=check_vma)


def pad_cols_to_ndev(n_cols: int, ndev: int, align: int = 1) -> int:
    """Smallest column count >= `n_cols` that tiles the mesh data axis
    for the psum_scatter histogram exchange: a multiple of
    lcm(ndev, align) (`align` carries a kernel layout constraint, e.g.
    the int8 store's 32-sublane grouping; pass ndev = data*feature for
    a 2-D mesh, where the per-feature-shard slice must itself tile the
    data axis).  Raises a clear ValueError on degenerate mesh sizes
    instead of letting lax.psum_scatter fail with a raw XLA tiling
    error downstream."""
    if ndev < 1 or align < 1:
        raise ValueError(
            f"pad_cols_to_ndev: mesh axis size ({ndev}) and alignment "
            f"({align}) must be >= 1; a zero-sized data axis cannot be "
            "tiled by any column padding")
    unit = math.lcm(int(ndev), int(align))
    return unit * int(math.ceil(max(int(n_cols), 1) / unit))


def check_scatter_divisible(axis: str, size: int, ndev: int) -> None:
    """Trace-time guard in front of `lax.psum_scatter`: raise a clear
    ValueError naming the axis, its size, and the mesh axis size when
    the scattered axis cannot tile the mesh.  The learners pad their
    stores with pad_cols_to_ndev so this never fires on the built-in
    paths; a caller wiring build_tree* directly without padding used to
    get a bare `assert` (gone under `python -O`, leaving XLA's raw
    shape error at the psum_scatter dispatch)."""
    if ndev > 1 and size % ndev:
        raise ValueError(
            f"psum_scatter needs the scattered axis '{axis}' (size "
            f"{size}) to be a multiple of the mesh data-axis size "
            f"({ndev}); pad the store columns with "
            f"learner.common.pad_cols_to_ndev "
            f"({pad_cols_to_ndev(size, ndev)} would tile)")


def check_tree_divergence(name: str, arrs, packed=None) -> None:
    """BENCH_SANITIZE divergence gate shared by both mesh learners
    (diagnostics/sanitize.py): the tree a build returned is replicated
    state — every device must hold the bitwise-identical copy, or a
    shard-local value leaked into the growth loop's control flow.
    Fingerprints one pytree shape for both learners (the packed tree
    vector plus leaf counts) so their divergence reports stay
    comparable across tree_growth modes.  No-op (one env read) unless
    the sanitizer is enabled; `packed` is computed only then when the
    caller has not already paid for it."""
    from ..diagnostics import sanitize
    if not sanitize.sanitize_enabled():
        return
    if packed is None:
        from .fused import pack_tree_arrays
        packed = pack_tree_arrays(arrs)
    sanitize.maybe_check_divergence(name, {"packed_tree": packed,
                                           "leaf_count": arrs.leaf_count})


def make_split_kw(cfg: Config) -> tuple:
    """Hashable (static-arg) split hyperparameters for ops.split.best_split
    (reference feature_histogram.hpp:281-300 gain math inputs)."""
    return tuple(sorted(dict(
        lambda_l1=float(cfg.lambda_l1), lambda_l2=float(cfg.lambda_l2),
        min_data_in_leaf=int(cfg.min_data_in_leaf),
        min_sum_hessian_in_leaf=float(cfg.min_sum_hessian_in_leaf),
        min_gain_to_split=float(cfg.min_gain_to_split)).items()))


def padded_bin_count(max_num_bin: int) -> int:
    """Bin axis padded to a lane-friendly multiple of 128."""
    return max(128, int(128 * math.ceil(max_num_bin / 128)))


def sentinel_bins_t(dataset) -> np.ndarray:
    """[N+1, C] int32 transpose of the STORE (per-feature rows, or EFB
    bundle columns) with a sentinel row at index N (bin 0) so padded
    gathers are branch-free."""
    bins_np = dataset.bins.astype(np.int32)
    pad = np.zeros((bins_np.shape[0], 1), np.int32)
    return np.concatenate([bins_np, pad], axis=1).T.copy()


def _default_pool_budget() -> float:
    """Unset histogram_pool_size defaults to a quarter of the device's
    memory when the backend reports it (16 GB v5e -> 4 GB: Epsilon-scale
    [255, 2000, 3, 256] caches fit and keep the 2x-cheaper subtraction
    path).  Remote-attached TPU plugins may not implement
    memory_stats() — every TPU this targets has >= 16 GB HBM, so the
    TPU fallback stays 4 GB (the round-4 Epsilon 255-bin sweep fell
    into bounded mode, 2x histogram passes, exactly because the
    tunneled backend reported no stats and the old fallback was
    1.5 GB); non-TPU hosts keep the conservative 1.5 GB."""
    try:
        import jax
        on_tpu = jax.default_backend() == "tpu"
    except Exception:
        return 1.5e9
    try:
        # remote plugins may RAISE (not return empty) from memory_stats;
        # the TPU fallback must survive either failure mode
        stats = jax.devices()[0].memory_stats()
        if stats and stats.get("bytes_limit"):
            return max(1.5e9, 0.25 * float(stats["bytes_limit"]))
    except Exception:
        pass
    return 4e9 if on_tpu else 1.5e9


def gather_scratch_capacity(np_rows: int) -> int:
    """Static row capacity of the gathered-histogram scratch for the
    smaller-child passes: in any round the smaller children of all
    splits partition subsets of their parents, so their sizes sum to
    <= ceil(N/2) by construction (the same bound that makes the
    reference's smaller/larger subtraction trick work,
    serial_tree_learner.cpp:344-422).  128-aligned so every tier is a
    whole lane tile."""
    cap = (np_rows + 1) // 2
    return max(128, 128 * int(math.ceil(cap / 128)))


def gather_capacity_tiers(cap: int) -> tuple:
    """Ascending static capacities for the gathered passes (full, /4,
    /16 of `cap`, deduped).  The per-pass capacity is picked at run time
    as the smallest tier holding the round's live rows — late rounds
    with small leaves drop to the small tiers, so the kernel cost
    tracks the live-row count instead of the static bound.  Three tiers
    bound the compile count (each tier is one kernel specialization,
    shared across call sites by the jit cache)."""
    full = max(128, 128 * int(math.ceil(cap / 128)))
    tiers = {full}
    for d in (4, 16):
        tiers.add(max(128, 128 * ((cap // d) // 128)))
    return tuple(sorted(tiers))


def gathered_scratch_fits(num_columns: int, np_rows: int,
                          bins_itemsize: int = 4,
                          limit_bytes: float = 0.0) -> bool:
    """Budget gate for the gathered path's transient scratch (the
    [F, cap] gathered bins plus [8, cap] vals materialized per pass —
    the analog of the HistogramPool cap for this buffer): it must fit
    comfortably next to the bin store and scores, so refuse when it
    would exceed ~15% of device memory."""
    cap = gather_scratch_capacity(np_rows)
    scratch = float(cap) * (num_columns * bins_itemsize + 8 * 4)
    if limit_bytes <= 0:
        try:
            import jax
            stats = jax.local_devices()[0].memory_stats()
            limit_bytes = float((stats or {}).get("bytes_limit", 0)) or 16e9
        except Exception:
            limit_bytes = 16e9
    return scratch <= 0.15 * limit_bytes


def resolve_hist_rows(cfg: Config, *, backend: str,
                      num_columns: int, np_rows: int,
                      bins_itemsize: int = 4) -> str:
    """Resolve the `hist_rows` knob to the mode a rounds learner runs.

    "masked" streams the full [F, N] bin store every histogram pass;
    "gathered" maintains the device-resident row partition and feeds
    the kernels only the leaf-contiguous segments they need.  "auto"
    picks gathered on TPU (the bandwidth-bound regime the optimization
    targets) — including multi-device data-parallel meshes, where the
    permutation, (offset, count) table, and gather scratch are per-shard
    locals inside the shard_map body (`np_rows` is then the PER-SHARD
    row count and sizes the scratch budget) — and masked on the CPU
    tier unless opted in."""
    mode = getattr(cfg, "hist_rows", "auto")
    from .. import log
    if mode == "auto":
        mode = "gathered" if backend == "pallas" else "masked"
    if mode == "gathered" and not gathered_scratch_fits(
            num_columns, np_rows, bins_itemsize):
        log.warning("hist_rows=gathered scratch would not fit the device "
                    "memory budget at this shape; using masked")
        return "masked"
    return mode


# `hist_exchange=auto` switches to psum_scatter only when the per-pass
# histogram payload is at least this many bytes: below it the full psum
# is cheaper than reduce-scatter + the per-leaf record allgather
# (mirroring the reference's allgather-vs-Recursive-Halving switch on
# small payloads, network.cpp ReduceScatter dispatch / SURVEY.md §2.8).
# The measured crossover on chip is captured by
# scripts/profile_hotpath.py (hist_exchange_ab_measured.json); override
# for on-chip tuning with LGBT_HIST_EXCHANGE_MIN_BYTES.
HIST_EXCHANGE_MIN_SCATTER_BYTES = 1 << 20


def _hist_exchange_threshold() -> int:
    import os
    raw = os.environ.get("LGBT_HIST_EXCHANGE_MIN_BYTES", "")
    if not raw:
        return HIST_EXCHANGE_MIN_SCATTER_BYTES
    try:
        return max(0, int(raw))
    except ValueError:
        from .. import log
        log.warning(f"ignoring malformed LGBT_HIST_EXCHANGE_MIN_BYTES="
                    f"{raw!r}")
        return HIST_EXCHANGE_MIN_SCATTER_BYTES


def resolve_hist_exchange(cfg: Config, *, ndev: int,
                          payload_bytes: float) -> str:
    """Resolve `hist_exchange` to the collective a data-parallel learner
    runs per histogram pass.  `payload_bytes` is the full reduced
    histogram size of one pass (K * F * 3 * B * 4); with a single device
    there is no exchange and the answer is always "psum" (a no-op)."""
    if ndev <= 1:
        return "psum"
    mode = getattr(cfg, "hist_exchange", "auto")
    if mode == "auto":
        return ("psum_scatter"
                if payload_bytes >= _hist_exchange_threshold() else "psum")
    return mode


def use_parent_hist_cache(cfg: Config, num_features: int,
                          num_bins_padded: int) -> bool:
    """Keep the [num_leaves, F, 3, B] per-leaf histogram cache for the
    parent-subtraction trick only while it fits the pool budget
    (reference HistogramPool cap, feature_histogram.hpp:313-475);
    otherwise learners histogram both children directly."""
    hist_cache_bytes = 4 * cfg.num_leaves * num_features * 3 * num_bins_padded
    budget = (cfg.histogram_pool_size * 1e6
              if cfg.histogram_pool_size > 0 else _default_pool_budget())
    return hist_cache_bytes <= budget
