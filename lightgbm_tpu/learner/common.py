"""Setup shared by the serial and fused tree learners — kept in one place
so the two learners (which must grow identical trees,
tests/test_parallel.py) cannot silently diverge.

The mesh/axis/shard_map wiring that used to live here moved to the
sharded-primitive layer (lightgbm_tpu/sharded/mesh.py); the names are
re-exported so existing imports keep working."""
from __future__ import annotations

import math

import numpy as np

from ..config import Config
from ..sharded.mesh import (  # noqa: F401 — re-exports (moved to sharded)
    HIST_EXCHANGE_MIN_SCATTER_BYTES, MultiHostRows, check_scatter_divisible,
    check_tree_divergence, compat_shard_map, mesh_axes, pad_cols_to_ndev,
    resolve_hist_exchange, row_shard_axes)


def make_split_kw(cfg: Config) -> tuple:
    """Hashable (static-arg) split hyperparameters for ops.split.best_split
    (reference feature_histogram.hpp:281-300 gain math inputs)."""
    return tuple(sorted(dict(
        lambda_l1=float(cfg.lambda_l1), lambda_l2=float(cfg.lambda_l2),
        min_data_in_leaf=int(cfg.min_data_in_leaf),
        min_sum_hessian_in_leaf=float(cfg.min_sum_hessian_in_leaf),
        min_gain_to_split=float(cfg.min_gain_to_split)).items()))


def padded_bin_count(max_num_bin: int) -> int:
    """Bin axis padded to a lane-friendly multiple of 128."""
    return max(128, int(128 * math.ceil(max_num_bin / 128)))


def sentinel_bins_t(dataset) -> np.ndarray:
    """[N+1, C] int32 transpose of the STORE (per-feature rows, or EFB
    bundle columns) with a sentinel row at index N (bin 0) so padded
    gathers are branch-free."""
    bins_np = dataset.dense_bins(site="bins_t").astype(np.int32)
    pad = np.zeros((bins_np.shape[0], 1), np.int32)
    return np.concatenate([bins_np, pad], axis=1).T.copy()


def _default_pool_budget() -> float:
    """Unset histogram_pool_size defaults to a quarter of the device's
    memory when the backend reports it (16 GB v5e -> 4 GB: Epsilon-scale
    [255, 2000, 3, 256] caches fit and keep the 2x-cheaper subtraction
    path).  Remote-attached TPU plugins may not implement
    memory_stats() — every TPU this targets has >= 16 GB HBM, so the
    TPU fallback stays 4 GB (the round-4 Epsilon 255-bin sweep fell
    into bounded mode, 2x histogram passes, exactly because the
    tunneled backend reported no stats and the old fallback was
    1.5 GB); non-TPU hosts keep the conservative 1.5 GB."""
    try:
        import jax
        on_tpu = jax.default_backend() == "tpu"
    except Exception:
        return 1.5e9
    try:
        # remote plugins may RAISE (not return empty) from memory_stats;
        # the TPU fallback must survive either failure mode
        stats = jax.devices()[0].memory_stats()
        if stats and stats.get("bytes_limit"):
            return max(1.5e9, 0.25 * float(stats["bytes_limit"]))
    except Exception:
        pass
    return 4e9 if on_tpu else 1.5e9


def gather_scratch_capacity(np_rows: int) -> int:
    """Static row capacity of the gathered-histogram scratch for the
    smaller-child passes: in any round the smaller children of all
    splits partition subsets of their parents, so their sizes sum to
    <= ceil(N/2) by construction (the same bound that makes the
    reference's smaller/larger subtraction trick work,
    serial_tree_learner.cpp:344-422).  128-aligned so every tier is a
    whole lane tile."""
    cap = (np_rows + 1) // 2
    return max(128, 128 * int(math.ceil(cap / 128)))


def gather_capacity_tiers(cap: int) -> tuple:
    """Ascending static capacities for the gathered passes (full, /4,
    /16 of `cap`, deduped).  The per-pass capacity is picked at run time
    as the smallest tier holding the round's live rows — late rounds
    with small leaves drop to the small tiers, so the kernel cost
    tracks the live-row count instead of the static bound.  Three tiers
    bound the compile count (each tier is one kernel specialization,
    shared across call sites by the jit cache)."""
    full = max(128, 128 * int(math.ceil(cap / 128)))
    tiers = {full}
    for d in (4, 16):
        tiers.add(max(128, 128 * ((cap // d) // 128)))
    return tuple(sorted(tiers))


def gathered_scratch_fits(num_columns: int, np_rows: int,
                          bins_itemsize: int = 4,
                          limit_bytes: float = 0.0) -> bool:
    """Budget gate for the gathered path's transient scratch (the
    [F, cap] gathered bins plus [8, cap] vals materialized per pass —
    the analog of the HistogramPool cap for this buffer): it must fit
    comfortably next to the bin store and scores, so refuse when it
    would exceed ~15% of device memory."""
    cap = gather_scratch_capacity(np_rows)
    scratch = float(cap) * (num_columns * bins_itemsize + 8 * 4)
    if limit_bytes <= 0:
        try:
            import jax
            stats = jax.local_devices()[0].memory_stats()
            limit_bytes = float((stats or {}).get("bytes_limit", 0)) or 16e9
        except Exception:
            limit_bytes = 16e9
    return scratch <= 0.15 * limit_bytes


def resolve_hist_rows(cfg: Config, *, backend: str,
                      num_columns: int, np_rows: int,
                      bins_itemsize: int = 4) -> str:
    """Resolve the `hist_rows` knob to the mode a rounds learner runs.

    "masked" streams the full [F, N] bin store every histogram pass;
    "gathered" maintains the device-resident row partition and feeds
    the kernels only the leaf-contiguous segments they need.  "auto"
    picks gathered on TPU (the bandwidth-bound regime the optimization
    targets) — including multi-device data-parallel meshes, where the
    permutation, (offset, count) table, and gather scratch are per-shard
    locals inside the shard_map body (`np_rows` is then the PER-SHARD
    row count and sizes the scratch budget) — and masked on the CPU
    tier unless opted in."""
    mode = getattr(cfg, "hist_rows", "auto")
    from .. import log
    if mode == "auto":
        mode = "gathered" if backend == "pallas" else "masked"
    if mode == "gathered" and not gathered_scratch_fits(
            num_columns, np_rows, bins_itemsize):
        log.warning("hist_rows=gathered scratch would not fit the device "
                    "memory budget at this shape; using masked")
        return "masked"
    return mode


def use_parent_hist_cache(cfg: Config, num_features: int,
                          num_bins_padded: int) -> bool:
    """Keep the [num_leaves, F, 3, B] per-leaf histogram cache for the
    parent-subtraction trick only while it fits the pool budget
    (reference HistogramPool cap, feature_histogram.hpp:313-475);
    otherwise learners histogram both children directly."""
    hist_cache_bytes = 4 * cfg.num_leaves * num_features * 3 * num_bins_padded
    budget = (cfg.histogram_pool_size * 1e6
              if cfg.histogram_pool_size > 0 else _default_pool_budget())
    return hist_cache_bytes <= budget
