"""Batched-rounds tree learner — the TPU throughput path.

The reference grows leaf-wise, one split at a time
(/root/reference/src/treelearner/serial_tree_learner.cpp:168-224), which on
TPU leaves the MXU nearly idle: a single leaf's histogram matmul has only
M=8 value rows (~6% utilization) and each split costs a full pass over the
rows.  This learner restructures the SAME split math into rounds:

- every round splits ALL currently-splittable leaves at once (when the
  `num_leaves` cap binds, the top-gain leaves win — the greedy criterion
  applied per round instead of per split);
- the smaller children of all K splits in a round are histogrammed in ONE
  multi-leaf pass (`ops/histogram.hist_multileaf`): vals rows are
  (grad·mask_k, hess·mask_k, mask_k) for K leaves → an [M=3K, C] @ [C, B]
  MXU matmul at M≈128, with the one-hot generation amortized over the
  whole round; larger children come from parent-histogram subtraction
  (serial_tree_learner.cpp smaller/larger trick, unchanged);
- the whole tree builds inside one `lax.while_loop` — zero host syncs
  (the reference's per-split host loop costs a device round-trip per
  split, which on remote-attached TPUs dominates everything).

When the cap never binds, a round-batched tree equals the leaf-wise tree:
splits of distinct leaves are independent, and every positive-gain leaf is
split in both policies.  They differ only in WHICH splits are kept once
`num_leaves` runs out (greedy-per-split vs greedy-per-round).

Data-parallel: rows sharded on the mesh "data" axis; histograms are
exchanged per pass either by full `lax.psum` or — the default at real
shapes — by `lax.psum_scatter` over the store-column axis, where each
device reduces and keeps only its F/ndev feature slice, split-searches
it, and all_gathers the per-leaf best-split records (the reference's
Network::ReduceScatter ownership model, data_parallel_tree_learner.cpp:
118-160; `hist_exchange` knob).  The gathered row partition is per-shard
local state, so `hist_rows=gathered` composes with both exchanges.
"""
from __future__ import annotations

import functools
import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..config import Config
from ..dataset import Dataset
from ..sharded.mesh import (check_scatter_divisible, check_tree_divergence,
                            mesh_axes, pad_cols_to_ndev,
                            resolve_hist_exchange)
from .common import (gather_capacity_tiers, gather_scratch_capacity,
                     make_split_kw, padded_bin_count, resolve_hist_rows,
                     sentinel_bins_t, use_parent_hist_cache)
from .fused import TreeArrays, tree_arrays_to_host
from ..jaxutil import bag_mask_dev, pad_rows_dev, slice_rows_dev, \
    unstack_scalars
from ..ops.histogram import (hist_multileaf_gathered, hist_multileaf_masked,
                             hist_sparse_gathered, hist_sparse_multileaf,
                             sparse_window_streams)
from ..ops.partition import partition_rows, partition_rows_sparse
from ..ops.split import (best_split, bundle_predicate_params,
                         combine_sharded_records, identity_feat_table,
                         leaf_output, maybe_unbundle, sharded_slice_search)
from ..tree import Tree

NEG_INF = -jnp.inf

# Leaves histogrammed per multi-leaf pass.  3·K is the M dimension of the
# hist matmul, and a LARGER K means FEWER full-row passes per round.  The
# ISOLATED kernel's per-pass cost is nearly flat in K on the int8 path
# (207 ms at K=1 vs 214 ms at K=128 on the north-star shape,
# profile_hotpath_measured.json), which predicts K=128 — one chunk per
# round — should win.  The in-learner A/B on chip says otherwise: at the
# north-star shape, end-to-end s/iter with K=128 was NOT faster than
# K=84 (rounds rarely split a full 128 leaves, and the masked kernel's
# work scales with the padded M, so late narrow rounds pay for leaves
# that aren't there).  84 (M=256) stays the measured default for every
# precision; bf16/f32 additionally slow down outright at M=384 (258 ms
# → 404 ms per pass).  Grown trees agree across K up to f32
# summation-order ulps (tests/test_rounds.py::
# test_leaves_per_batch_k_independent) and LGBT_LEAVES_PER_BATCH
# overrides the default for on-chip tuning.
import os as _os


def _clamp_k(v: int) -> int:
    """Clamp to [1, 336]: 3K is the matmul M dim and the masked kernel's
    VMEM vals block is [3K, chunk] — 336 (M=1024) is a safe ceiling well
    past any profitable K (the chunk cap in ops/histogram.py shrinks the
    row chunk to keep the block inside VMEM)."""
    c = max(1, min(v, 336))
    if c != v:
        from .. import log
        log.warning(f"LGBT_LEAVES_PER_BATCH={v} clamped to {c}")
    return c


def _leaves_per_batch_from_env() -> Optional[int]:
    """Defensive parse (a malformed value must not break every import);
    None when unset — the module default (84) then applies."""
    raw = _os.environ.get("LGBT_LEAVES_PER_BATCH", "")
    if not raw:
        return None
    try:
        v = int(raw)
    except ValueError:
        from .. import log
        log.warning(f"ignoring malformed LGBT_LEAVES_PER_BATCH={raw!r}; "
                    "using the default (84)")
        return None
    return _clamp_k(v)


# K for one masked histogram pass: env override, else the chip-measured
# 84 (see the block comment above — the kernel-level case for K=128 on
# int8 did not survive the end-to-end A/B).  Read at call time by
# build_tree_rounds so tests can monkeypatch it.
LEAVES_PER_BATCH = _leaves_per_batch_from_env() or 84


def _psum(x, axis):
    return jax.lax.psum(x, axis) if axis is not None else x


def build_tree_rounds(bins, grad, hess, row_mask, num_bins, is_cat, fmask,
                      ftbl=None, unb=None, *,
                      num_leaves: int, num_bins_padded: int, split_kw: tuple,
                      max_num_bin: int = 0,
                      max_depth: int, min_data_in_leaf: int,
                      min_sum_hessian_in_leaf: float,
                      data_axis: Optional[str] = None,
                      feature_axis: Optional[str] = None,
                      backend: str = "xla",
                      input_dtype: str = "float32",
                      max_rounds: int = 0,
                      cache_parent_hist: bool = True,
                      hist_rows: str = "masked",
                      hist_exchange: str = "psum",
                      num_devices: int = 1,
                      num_feature_shards: int = 1,
                      leaves_per_batch: int = 0,
                      sparse: bool = False):
    """Grow one tree in batched rounds.  Shapes as learner/fused.build_tree.
    Returns (TreeArrays, leaf_id, stats) — stats is a [4] f32 vector:
    (rows processed by histogram kernels — global across shards — the
    live-traffic metric behind the gathered-vs-masked A/B; per-device
    histogram-exchange payload bytes; per-device best-split-record
    allgather bytes; stored sparse entries processed — global, 0 on
    the dense path).

    hist_rows="gathered" maintains a device-resident row partition
    inside the while_loop: a [N] row permutation grouped by leaf plus
    per-leaf (offset, count), stably compacted after each round's
    partition_rows exactly like the reference's DataPartition::Split
    (data_partition.hpp:80-130).  Histogram passes then gather only the
    leaf-contiguous segments they need into a static scratch (sum of
    smaller children <= N/2 by construction) instead of streaming all N
    rows; bagged/GOSS-dropped rows never enter the permutation.  Under
    shard_map everything — permutation, (offset, count) table, scratch,
    capacity tiers (static at ceil(N_local/2)) — is per-shard local
    state over the shard's row block; per-shard counts diverge, but the
    tier lax.cond branches contain no collectives, so shards may pick
    different tiers freely.  "masked" is the original full-stream path.

    hist_exchange="psum_scatter" (static; with data_axis set and
    num_devices the data-axis size) replaces the full [K, F, 3, B]
    histogram psum with a reduce-scatter over the store-column axis:
    each device reduces and keeps only its F/num_devices column slice
    (the reference's ReduceScatter ownership model,
    data_parallel_tree_learner.cpp:118-160), runs best-split search on
    that slice only (bundle-aware: the slice is unbundled per shard via
    ops/split.unbundle_hist_local), then all_gathers the per-leaf
    packed records and combines them (max gain, ties to the smallest
    feature id — ops/split.combine_sharded_records).  Per-device comms
    drop ~num_devices x always; split-search work drops too on the
    identity store (the bundled path re-scans the full original-feature
    layout per shard — EFB already shrank the histogrammed width).  The
    parent-histogram cache holds column SLICES in this mode
    (num_devices x less memory).  F must then divide evenly by
    num_devices (callers pad the store).

    feature_axis adds the 2-D (data x feature) mesh topology
    (docs/Distributed-Data.md): rows shard over BOTH axes (every device
    holds all store columns of its row block); the exchange
    reduce-scatters over the FEATURE axis first and then psums only
    the resulting F/num_feature_shards slice over the DATA axis — the
    axis meant to span hosts moves the slice, not the full store —
    leaving each device its column slice fully reduced across all
    num_devices * num_feature_shards row shards.  Split records combine over the
    feature axis; leaf totals, control flow, and the grown tree stay
    bitwise replicated across the whole mesh, so 2-D trees are
    IDENTICAL to the 1-D psum and psum_scatter trees (the MULTICHIP
    dryrun gate).  F must divide evenly by num_feature_shards.

    `bins` holds STORE columns (bundled under EFB); num_bins/is_cat/fmask
    are per-ORIGINAL-feature.  `ftbl` is the [5, F] feature→column table
    (identity when unbundled) and `unb` the optional unbundle-gather
    tables — every histogram is unbundled before split search, so split
    records, TreeArrays, and leaf partitioning all speak original
    (feature, threshold) space; only partition_rows sees store columns,
    through the translated store-space predicate.

    cache_parent_hist=False bounds tree-state memory (the analog of the
    reference HistogramPool cap, feature_histogram.hpp:313-475): instead
    of keeping every leaf's [F, 3, B] histogram for the parent-subtraction
    trick, BOTH children are histogrammed directly — 2x histogram passes
    per round, O(1) leaf-hist memory.  The learner picks this mode when
    L*F*3*B*4 bytes exceeds the histogram_pool_size budget.

    sparse=True switches the row feed to the nonzero-iterating kernels
    (docs/Sparse.md): `bins` is then the sparse-store pytree
    (cols [Nloc, R], bins [Nloc, R], zero_bin [F], e_row, e_flat,
    e_valid window streams — stream leaves carry a leading stacked-shard
    axis under shard_map) and every histogram/partition touches only
    stored entries, with the zero bin reconstructed from per-leaf
    totals.  The reduced histogram keeps the dense [K, F, 3, B] layout,
    so hist_exchange (psum / psum_scatter slice ownership) and the
    round/compaction logic compose unchanged; gathered mode permutes
    the ELL row segments exactly like dense rows.  The stats vector
    gains a 4th element: stored entries touched by histogram kernels
    (global across shards — the tree/sparse_nnz_touched counter)."""
    if sparse:
        sp_cols, sp_bins, sp_zb = bins[0], bins[1], bins[2]
        # stream leaves arrive stacked with a leading shard axis (one
        # block per shard under shard_map); squeeze it
        sp_streams = tuple((a[0] if a.ndim == 3 else a) for a in bins[3:6])
        sp_slots = bins[6][0] if bins[6].ndim == 2 else bins[6]
        spt = (sp_cols, sp_bins, sp_zb) + sp_streams + (sp_slots,)
        Nloc = sp_cols.shape[0]
        F = sp_zb.shape[0]
        # stored entries per masked pass (static shape, traced value)
        nnz_pass = jnp.sum((sp_cols < F).astype(jnp.float32))
    else:
        F, Nloc = bins.shape
    L = num_leaves
    B = num_bins_padded
    K = leaves_per_batch or LEAVES_PER_BATCH
    n_chunks = (L + K - 1) // K
    gathered = hist_rows == "gathered"
    # rows shard over every mesh axis present; under psum_scatter the
    # store-column axis scatters over ONE of them — the feature axis on
    # a 2-D (data x feature) mesh, else the data axis (1-D)
    row_axes = tuple(a for a in (data_axis, feature_axis)
                     if a is not None) or None
    sc_axis = feature_axis if feature_axis is not None else data_axis
    hx = hist_exchange == "psum_scatter" and sc_axis is not None
    nd = (num_feature_shards if feature_axis is not None
          else (num_devices if data_axis is not None else 1))
    if hx:
        # trace-time guard with a named ValueError (the learner pads the
        # store, so only direct build_tree_rounds callers can trip it)
        check_scatter_divisible("store columns", F, nd)
    Fs = F // nd if hx else F

    def exchange(h):
        """Reduce a LOCAL histogram [..., F, 3, B] across the row axes:
        full psum, or reduce(-scatter) keeping this shard's [Fs, 3, B]
        store-column slice.  On the 2-D mesh the reduction decomposes
        as reduce-scatter over the FEATURE axis first (dropping to the
        F/df slice while still inside the intra-host axis) and then a
        psum of only that slice over the DATA axis — the axis that
        spans hosts moves F/df columns, not F (one-step psum_scatter
        on a 1-D mesh)."""
        if row_axes is None:
            return h
        if hx:
            h = jax.lax.psum_scatter(h, sc_axis,
                                     scatter_dimension=h.ndim - 3,
                                     tiled=True)
            if data_axis is not None and feature_axis is not None:
                h = jax.lax.psum(h, data_axis)
            return h
        return jax.lax.psum(h, row_axes)

    # per-device reduced payload per collective leg: the scatter leg
    # keeps the F/nd slice; the 2-D mesh adds the data-axis psum of
    # that same slice as a second leg
    hx_legs = 2 if (hx and data_axis is not None
                    and feature_axis is not None) else 1

    def _exchange_bytes(k2: int) -> float:
        """Per-device reduced-histogram payload of one k2-leaf pass:
        the full tensor under psum, the F/nd slice (times the collective
        legs of the 2-D decomposition) under psum_scatter."""
        if row_axes is None:
            return 0.0
        if hx:
            return 4.0 * k2 * Fs * 3 * B * hx_legs
        return 4.0 * k2 * F * 3 * B

    def _records_bytes(k2: int) -> float:
        """Per-device payload of the best-split-record allgather (only
        the psum_scatter path exchanges records)."""
        return 4.0 * nd * k2 * 11 if hx else 0.0

    if gathered:
        # static capacity tiers: smaller-child passes are bounded by
        # ceil(N/2); direct large-child passes (bounded-memory mode) by N
        tiers_all = gather_capacity_tiers(Nloc)
        tiers_small = gather_capacity_tiers(gather_scratch_capacity(Nloc))
        if row_axes is not None:
            # the ceil(N/2) smaller-child bound is GLOBAL: smaller/larger
            # is decided on global counts, so one shard's local share of
            # the globally-smaller children can reach ALL of its rows.
            # Keep the N/2 tier (it catches the typical balanced pass,
            # preserving the rows-touched win) but make the full-Nloc
            # tier reachable so a skewed shard never overflows the
            # scratch and silently drops rows.
            tiers_small = tuple(sorted(set(tiers_small + tiers_all)))
    if ftbl is None:
        ftbl = identity_feat_table(num_bins)
    # Termination is governed by the while_loop predicate (no positive gain
    # or num_leaves reached); R is only a provably non-binding safety bound:
    # any round that runs splits >=1 leaf, so L-1 rounds suffice even for a
    # chain-shaped tree (serial_tree_learner.cpp:203-224 stopping rule).
    R = max_rounds if max_rounds > 0 else L - 1
    skw = dict(split_kw)
    l1, l2 = skw["lambda_l1"], skw["lambda_l2"]
    # int8-stored bins (value-128, see ops/histogram bin_offset) stay
    # narrow: a [F, N] int32 copy would be 4x the HBM (30.8 GB at Expo
    # shape); every consumer widens in fused ops / kernel VMEM
    if sparse:
        binsf = None
    elif bins.dtype == jnp.int8:
        binsf = bins
    else:
        binsf = bins.astype(jnp.int32)

    def hist_masked(lid_, sl_):
        """One masked multi-leaf pass over the full store — dense
        streaming or nonzero-iterating per the static `sparse` flag;
        both return [K, F, 3, B]."""
        if sparse:
            return hist_sparse_multileaf(
                spt, lid_, gh8, sl_, num_columns_padded=F,
                num_bins_padded=B, backend=backend,
                input_dtype=input_dtype)
        return hist_multileaf_masked(
            binsf, lid_, gh8, sl_, num_bins_padded=B, backend=backend,
            input_dtype=input_dtype, max_num_bin=max_num_bin,
            num_leaves=L)

    def find_best_batch(hists, sums):
        """hists [K2, C, 3, B] reduced STORE histograms (C = F, or this
        shard's Fs slice under psum_scatter), sums [K2, 3] → packed recs
        [K2, 11] in ORIGINAL feature space (unbundled per leaf), with
        the can-split gate applied (depth gate at selection time).

        psum_scatter: each shard split-searches only its column slice
        (ops/split.sharded_slice_search — unbundled per shard, or the
        identity store's metadata dynamic-slice), then the [nd, K2, 11]
        record allgather picks each leaf's max gain with ties broken by
        smallest feature id (ops/split.combine_sharded_records — the
        full search's flat-argmax tie-break, shard-order independent)."""
        if hx:
            off = jax.lax.axis_index(sc_axis) * Fs
            if unb is None:
                nb_s = jax.lax.dynamic_slice_in_dim(num_bins, off, Fs)
                ic_s = jax.lax.dynamic_slice_in_dim(is_cat, off, Fs)
                fm_s = jax.lax.dynamic_slice_in_dim(fmask, off, Fs)
            else:
                nb_s = ic_s = fm_s = None

        def one(h, s):
            if hx:
                p = sharded_slice_search(
                    h, s, off=off, nb_s=nb_s, ic_s=ic_s, fm_s=fm_s,
                    num_bins=num_bins, is_cat=is_cat, fmask=fmask,
                    unb=unb, skw=skw)
            else:
                rec = best_split(maybe_unbundle(h, unb, s),
                                 num_bins, is_cat, fmask,
                                 s[0], s[1], s[2], **skw)
                p = rec.packed()
            can = ((s[2] >= 2 * min_data_in_leaf)
                   & (s[1] >= 2 * min_sum_hessian_in_leaf))
            gain = jnp.where(can & jnp.isfinite(p[0]) & (p[0] > 0),
                             p[0], NEG_INF)
            return p.at[0].set(gain)

        recs = jax.vmap(one)(hists, sums)
        if hx:
            recs = combine_sharded_records(recs, sc_axis)
        return recs

    # ---- root ---------------------------------------------------------------
    gh8 = jnp.zeros((8, Nloc), jnp.float32)
    gh8 = gh8.at[0].set(grad * row_mask).at[1].set(hess * row_mask)
    gh8 = gh8.at[2].set(row_mask)
    lid0 = jnp.zeros(Nloc, jnp.int32)
    h0 = hist_masked(lid0, jnp.zeros(1, jnp.int32))
    if hx:
        # leaf totals from the LOCAL pass (any single store column's bin
        # sums give them; store column 0 is always real) + one tiny
        # psum — the scattered histogram no longer holds column 0 on
        # every shard
        ls = jnp.stack([jnp.sum(h0[0, 0, 0, :]), jnp.sum(h0[0, 0, 1, :]),
                        jnp.sum(h0[0, 0, 2, :])])
        root_sums = jax.lax.psum(ls, row_axes)
        cnt = root_sums[2]
        hist0 = exchange(h0[0])                         # [Fs, 3, B]
    else:
        hist0 = _psum(h0[0], row_axes)                  # [F, 3, B]
        sum_g = jnp.sum(hist0[0, 0, :])
        sum_h = jnp.sum(hist0[0, 1, :])
        cnt = jnp.sum(hist0[0, 2, :])
        root_sums = jnp.stack([sum_g, sum_h, cnt])

    leaf_id = jnp.zeros(Nloc, jnp.int32)
    if gathered:
        # initial permutation: live (mask > 0) rows first in row order —
        # root's segment — with sampled-out rows parked past n_active,
        # outside every leaf segment forever (they still carry leaf ids
        # and are moved by partition_rows, but no histogram reads them)
        posn0 = jax.lax.iota(jnp.int32, Nloc)
        live0 = (row_mask > 0).astype(jnp.int32)
        ecs0 = jnp.cumsum(live0) - live0           # lives before each row
        n_active = jnp.sum(live0)
        dest0 = jnp.where(live0 > 0, ecs0, n_active + (posn0 - ecs0))
        perm = jnp.zeros(Nloc, jnp.int32).at[dest0].set(posn0)
        leaf_off = jnp.zeros(L, jnp.int32)
        leaf_cnt = jnp.zeros(L, jnp.int32).at[0].set(n_active)
    else:
        perm = jnp.zeros(0, jnp.int32)
        leaf_off = jnp.zeros(0, jnp.int32)
        leaf_cnt = jnp.zeros(0, jnp.int32)
    # (rows touched by hist kernels, exchange bytes, record bytes,
    # sparse entries touched) — the root contributes one masked
    # full-stream pass + one exchange
    stats = jnp.asarray([float(Nloc), _exchange_bytes(1),
                         _records_bytes(1), 0.0], jnp.float32)
    if sparse:
        stats = stats.at[3].add(nnz_pass)
    leaf_best = jnp.full((L, 11), NEG_INF, jnp.float32).at[0].set(
        find_best_batch(hist0[None], root_sums[None])[0])
    leaf_depth = jnp.zeros(L, jnp.int32)
    leaf_parent = jnp.full(L, -1, jnp.int32)
    leaf_side = jnp.zeros(L, jnp.int32)
    # under psum_scatter the cache holds this shard's column SLICES
    leaf_hist = (jnp.zeros((L,) + hist0.shape, jnp.float32).at[0].set(hist0)
                 if cache_parent_hist
                 else jnp.zeros((1, 1, 1, 1), jnp.float32))

    arrs = TreeArrays(
        split_feature=jnp.zeros(L - 1, jnp.int32),
        threshold_bin=jnp.zeros(L - 1, jnp.int32),
        is_cat=jnp.zeros(L - 1, bool),
        left_child=jnp.zeros(L - 1, jnp.int32),
        right_child=jnp.zeros(L - 1, jnp.int32),
        split_gain=jnp.zeros(L - 1, jnp.float32),
        internal_value=jnp.zeros(L - 1, jnp.float32),
        internal_count=jnp.zeros(L - 1, jnp.float32),
        # leaf 0 stays 0.0 until a split assigns it: a tree that never
        # splits must contribute zero score (the sync path discards such
        # trees; the pipelined path applies leaf values before it can know)
        leaf_value=jnp.zeros(L, jnp.float32),
        leaf_count=jnp.zeros(L, jnp.float32).at[0].set(cnt),
        leaf_depth=jnp.zeros(L, jnp.int32),
        num_leaves=jnp.int32(1),
    )

    def round_body(st):
        (rnd, leaf_id, leaf_best, leaf_depth, leaf_parent, leaf_side,
         leaf_hist, perm, leaf_off, leaf_cnt, stats, arrs) = st
        n_leaves = arrs.num_leaves

        # ---- select this round's splits (top-gain within the cap) ---------
        gated = jnp.where((max_depth <= 0) | (leaf_depth < max_depth),
                          leaf_best[:, 0], NEG_INF)
        order = jnp.argsort(-gated).astype(jnp.int32)       # [L]
        sgain = gated[order]
        remaining = L - n_leaves
        slot = jax.lax.broadcasted_iota(jnp.int32, (L,), 0)
        do = (sgain > 0) & (slot < remaining)               # [L] sorted slots
        prefix = jnp.cumsum(do.astype(jnp.int32)) - do.astype(jnp.int32)
        m = jnp.sum(do.astype(jnp.int32))

        pl_ = order                                          # parent leaf/slot
        rec = leaf_best[pl_]                                 # [L, 11]
        feat = rec[:, 1].astype(jnp.int32)
        thr = rec[:, 2].astype(jnp.int32)
        catf = is_cat[feat]
        new_leaf = n_leaves + prefix                         # [L]
        node = (n_leaves - 1) + prefix                       # [L]
        l_sums = rec[:, 3:6]
        r_sums = rec[:, 6:9]

        # ---- partition all rows in one pass -------------------------------
        # per-LEAF lookup of (split column, threshold, is-cat, new leaf)
        # then the per-row bin read and move — fused in one pallas pass
        # (ops/partition.py; XLA fallback composes the one-hot matmuls of
        # ops/lookup.py there).  XLA's [Nloc] table gather runs at
        # <1 GB/s on TPU and cost more than the histogram kernel
        # (65 ms/table at N=4M); new_leaf > 0 ⟺ leaf splits, leaf 0
        # is never a NEW leaf, so 0 table rows mean "stay".  The split
        # (feat, thr) is ORIGINAL space; the table carries the translated
        # STORE-space predicate (ops/split.bundle_predicate_params), so
        # bundled columns partition without ever materializing original
        # bins
        colv, Tv, lov, hi1v, dlv = bundle_predicate_params(
            ftbl, feat, thr, catf)
        tbl_idx = jnp.where(do, pl_, L)                      # drop-slot L
        zeros = jnp.zeros(L + 1, jnp.float32)

        def srow(v):
            return zeros.at[tbl_idx].set(v.astype(jnp.float32), mode="drop")

        tbl = jnp.stack([srow(colv), srow(Tv), srow(catf), srow(new_leaf),
                         srow(lov), srow(hi1v), srow(dlv)])
        if sparse:
            leaf_id2 = partition_rows_sparse(sp_cols, sp_bins, sp_zb,
                                             leaf_id, tbl,
                                             num_slots=L + 1)
        else:
            leaf_id2 = partition_rows(binsf, leaf_id, tbl,
                                      num_slots=L + 1, backend=backend,
                                      num_bins_padded=B)

        # ---- stable row compaction (DataPartition::Split, vectorized) -----
        # Each splitting leaf's contiguous segment of `perm` divides into
        # a stay-prefix (rows keeping the parent id, original order) and
        # a moved-suffix (rows taking the new id) — O(N) with one cumsum
        # and a scatter, no sort.  Parked (sampled-out) rows sit past
        # n_active and keep their positions.
        if gathered:
            posn = jax.lax.iota(jnp.int32, Nloc)
            n_act = jnp.sum(leaf_cnt)
            ol = jnp.take(leaf_id, perm)                 # old leaf per slot
            nl = jnp.take(leaf_id2, perm)                # new leaf per slot
            stay = nl == ol
            csp = jnp.concatenate([jnp.zeros(1, jnp.int32),
                                   jnp.cumsum(stay.astype(jnp.int32))])
            soff = jnp.take(leaf_off, ol)                # segment starts
            seg_stays = jnp.take(csp, soff)
            rstay = csp[:Nloc] - seg_stays               # stays before pos
            ns_row = jnp.take(csp, soff + jnp.take(leaf_cnt, ol)) - seg_stays
            dest = soff + jnp.where(stay, rstay,
                                    ns_row + (posn - soff) - rstay)
            dest = jnp.where(posn >= n_act, posn, dest)
            perm2 = jnp.zeros_like(perm).at[dest].set(perm)
            # split each parent's (offset, count): parent keeps the
            # stay-prefix, the new leaf takes the moved suffix
            ns_leaf = (jnp.take(csp, leaf_off + leaf_cnt)
                       - jnp.take(csp, leaf_off))        # [L] stay counts
            ns_p = jnp.take(ns_leaf, pl_)
            nii = jnp.where(do, new_leaf, L)
            pii = jnp.where(do, pl_, L)
            leaf_off2 = leaf_off.at[nii].set(
                jnp.take(leaf_off, pl_) + ns_p, mode="drop")
            leaf_cnt2 = (leaf_cnt.at[nii].set(
                jnp.take(leaf_cnt, pl_) - ns_p, mode="drop")
                .at[pii].set(ns_p, mode="drop"))
        else:
            perm2, leaf_off2, leaf_cnt2 = perm, leaf_off, leaf_cnt

        # ---- tree arrays (batched Tree::Split) ----------------------------
        nodei = jnp.where(do, node, L - 1)                   # drop idx
        lvali = jnp.where(do, pl_, L)
        nvali = jnp.where(do, new_leaf, L)
        pn = leaf_parent[pl_]
        side = leaf_side[pl_]
        lpar = jnp.where(do & (pn >= 0) & (side == 0), pn, L - 1)
        rpar = jnp.where(do & (pn >= 0) & (side == 1), pn, L - 1)
        child_depth = leaf_depth[pl_] + 1
        arrs2 = arrs._replace(
            split_feature=arrs.split_feature.at[nodei].set(
                feat, mode="drop"),
            threshold_bin=arrs.threshold_bin.at[nodei].set(thr, mode="drop"),
            is_cat=arrs.is_cat.at[nodei].set(catf, mode="drop"),
            split_gain=arrs.split_gain.at[nodei].set(rec[:, 0], mode="drop"),
            internal_value=arrs.internal_value.at[nodei].set(
                arrs.leaf_value[pl_], mode="drop"),
            internal_count=arrs.internal_count.at[nodei].set(
                l_sums[:, 2] + r_sums[:, 2], mode="drop"),
            left_child=arrs.left_child.at[lpar].set(
                node, mode="drop").at[nodei].set(~pl_, mode="drop"),
            right_child=arrs.right_child.at[rpar].set(
                node, mode="drop").at[nodei].set(~new_leaf, mode="drop"),
            leaf_value=arrs.leaf_value.at[lvali].set(
                rec[:, 9], mode="drop").at[nvali].set(rec[:, 10],
                                                      mode="drop"),
            leaf_count=arrs.leaf_count.at[lvali].set(
                l_sums[:, 2], mode="drop").at[nvali].set(r_sums[:, 2],
                                                         mode="drop"),
            leaf_depth=arrs.leaf_depth.at[lvali].set(
                child_depth, mode="drop").at[nvali].set(child_depth,
                                                        mode="drop"),
            num_leaves=n_leaves + m,
        )
        leaf_depth2 = leaf_depth.at[lvali].set(
            child_depth, mode="drop").at[nvali].set(child_depth, mode="drop")
        leaf_parent2 = leaf_parent.at[lvali].set(
            node, mode="drop").at[nvali].set(node, mode="drop")
        leaf_side2 = leaf_side.at[lvali].set(0, mode="drop").at[nvali].set(
            1, mode="drop")

        # ---- batched smaller-child histograms -----------------------------
        small_is_left = l_sums[:, 2] <= r_sums[:, 2]
        small_leaf = jnp.where(small_is_left, pl_, new_leaf)
        large_leaf = jnp.where(small_is_left, new_leaf, pl_)
        small_sums = jnp.where(small_is_left[:, None], l_sums, r_sums)
        large_sums = jnp.where(small_is_left[:, None], r_sums, l_sums)

        # early rounds have few splittable leaves (1, 2, 4, ... for a
        # balanced tree) but a fixed-K pass pays the full Mp=3K matmul
        # M dimension for mostly-empty slots — tiered kernels cut the
        # early rounds' MXU work: a chunk with <= 8 active slots runs
        # the K=8 kernel (rounds 1-4 of a balanced tree), <= 32 the
        # K=32 kernel (rounds 5-6; the matmul is ~62% of the pass once
        # the compares are narrow, so Mp 256->96 matters), else full K.
        # Results are zero-padded to Kc — inactive slots are dropped
        # downstream, so the padding rows are never read.
        K_SMALL = min(8, K)
        K_MID = min(32, K)

        def hist_tiered(slv, dk, Kc):
            def full_call(slv_k):
                if sparse:
                    return hist_sparse_multileaf(
                        spt, leaf_id2, gh8, slv_k, num_columns_padded=F,
                        num_bins_padded=B, backend=backend,
                        input_dtype=input_dtype)
                return hist_multileaf_masked(
                    binsf, leaf_id2, gh8, slv_k, num_bins_padded=B,
                    backend=backend, input_dtype=input_dtype,
                    max_num_bin=max_num_bin, num_leaves=L)

            def at(Kt):
                h = full_call(slv[:Kt])
                if Kt >= Kc:
                    return h
                return jnp.concatenate(
                    [h, jnp.zeros((Kc - Kt,) + h.shape[1:], h.dtype)],
                    axis=0)

            if Kc <= K_SMALL:
                return full_call(slv)

            def full_or_mid(_):
                if Kc <= K_MID:
                    return at(Kc)
                # gate on the REAL precondition (no active slot past
                # the window), not on the count — robust even if the
                # sorted-prefix layout of `do` ever changes
                return jax.lax.cond(~jnp.any(dk[K_MID:]),
                                    lambda _: at(K_MID),
                                    lambda _: at(Kc), None)

            return jax.lax.cond(~jnp.any(dk[K_SMALL:]),
                                lambda _: at(K_SMALL), full_or_mid, None)

        def hist_gathered_tiered(slv, tiers):
            """Gathered histogram of the slots' leaf segments at the
            smallest static capacity tier holding this pass's live rows
            (lax.cond picks the tier at run time; every tier is one
            fixed-shape kernel, so nothing retraces round to round).
            Returns ([Kc, F, 3, B] hists, f32 rows processed, f32
            stored entries processed — 0 on the dense path)."""
            sc = jnp.clip(slv, 0, L - 1)
            act = slv >= 0
            so = jnp.where(act, jnp.take(leaf_off2, sc), 0)
            sn = jnp.where(act, jnp.take(leaf_cnt2, sc), 0)
            total = jnp.sum(sn)

            def call(cap):
                def f(_):
                    if sparse:
                        return hist_sparse_gathered(
                            (sp_cols, sp_bins, sp_zb), gh8, perm2, so,
                            sn, capacity=cap, num_columns_padded=F,
                            num_bins_padded=B)
                    return (hist_multileaf_gathered(
                        binsf, gh8, perm2, so, sn, capacity=cap,
                        num_bins_padded=B, backend=backend,
                        input_dtype=input_dtype,
                        max_num_bin=max_num_bin), jnp.float32(0))
                return f

            def pick(i):
                if i == len(tiers) - 1:
                    return call(tiers[i])
                return lambda _: jax.lax.cond(
                    total <= tiers[i], call(tiers[i]), pick(i + 1), None)

            rt_pass = jnp.float32(tiers[-1])
            for cap in tiers[-2::-1]:
                rt_pass = jnp.where(total <= cap, jnp.float32(cap), rt_pass)
            h, nz = pick(0)(None)
            return h, rt_pass, nz

        leaf_best2 = leaf_best
        leaf_hist2 = leaf_hist
        stats2 = stats
        for c in range(n_chunks):
            s = c * K
            Kc = min(K, L - s)                               # last chunk short
            dk = do[s:s + Kc]                                # [Kc]
            sl = small_leaf[s:s + Kc]

            def do_chunk(args, s=s, Kc=Kc, dk=dk, sl=sl):
                leaf_best2, leaf_hist2, stv = args
                slv = jnp.where(dk, sl, -1)                  # -1 = empty slot
                if gathered:
                    h_small, rtp, nz = hist_gathered_tiered(slv,
                                                            tiers_small)
                    stv = stv.at[0].add(rtp).at[3].add(nz)
                else:
                    h_small = hist_tiered(slv, dk, Kc)
                    stv = stv.at[0].add(jnp.float32(Nloc))
                    if sparse:
                        stv = stv.at[3].add(nnz_pass)
                h_small = exchange(h_small)        # [Kc, F|Fs, 3, B]
                stv = stv.at[1].add(_exchange_bytes(Kc))
                if cache_parent_hist:
                    h_large = leaf_hist2[pl_[s:s + Kc]] - h_small
                else:
                    llv = jnp.where(dk, large_leaf[s:s + Kc], -1)
                    if gathered:
                        h_large, rtp, nz = hist_gathered_tiered(llv,
                                                                tiers_all)
                        stv = stv.at[0].add(rtp).at[3].add(nz)
                    else:
                        h_large = hist_tiered(llv, dk, Kc)
                        stv = stv.at[0].add(jnp.float32(Nloc))
                        if sparse:
                            stv = stv.at[3].add(nnz_pass)
                    h_large = exchange(h_large)
                    stv = stv.at[1].add(_exchange_bytes(Kc))
                rec_s = find_best_batch(h_small, small_sums[s:s + Kc])
                rec_l = find_best_batch(h_large, large_sums[s:s + Kc])
                stv = stv.at[2].add(2 * _records_bytes(Kc))
                sil = small_is_left[s:s + Kc, None]
                recL = jnp.where(sil, rec_s, rec_l)
                recR = jnp.where(sil, rec_l, rec_s)
                li = jnp.where(dk, pl_[s:s + Kc], L)
                ni = jnp.where(dk, new_leaf[s:s + Kc], L)
                lb = leaf_best2.at[li].set(recL, mode="drop").at[ni].set(
                    recR, mode="drop")
                if cache_parent_hist:
                    hL = jnp.where(sil[:, :, None, None], h_small, h_large)
                    hR = jnp.where(sil[:, :, None, None], h_large, h_small)
                    lh = leaf_hist2.at[li].set(hL, mode="drop").at[ni].set(
                        hR, mode="drop")
                else:
                    lh = leaf_hist2
                return lb, lh, stv

            def skip_chunk(args):
                return args

            # graftlint: allow(divergent-collective) — dk slices `do`, derived from the replicated leaf_best records (psum/combine_sharded_records outputs carried through the while_loop), so every shard computes the identical predicate and takes the same branch; the DivergenceSanitizer checks the products at run time
            leaf_best2, leaf_hist2, stats2 = jax.lax.cond(
                jnp.any(dk), do_chunk, skip_chunk,
                (leaf_best2, leaf_hist2, stats2))

        return (rnd + 1, leaf_id2, leaf_best2, leaf_depth2, leaf_parent2,
                leaf_side2, leaf_hist2, perm2, leaf_off2, leaf_cnt2,
                stats2, arrs2)

    def round_cond(st):
        rnd, leaf_best, leaf_depth, arrs = st[0], st[2], st[3], st[-1]
        gated = jnp.where((max_depth <= 0) | (leaf_depth < max_depth),
                          leaf_best[:, 0], NEG_INF)
        return ((rnd < R) & (arrs.num_leaves < L)
                & jnp.any(gated > 0))

    st = (jnp.int32(0), leaf_id, leaf_best, leaf_depth, leaf_parent,
          leaf_side, leaf_hist, perm, leaf_off, leaf_cnt, stats,
          arrs)
    st = jax.lax.while_loop(round_cond, round_body, st)
    # rows and sparse entries are summed across shards (global
    # traffic); the byte counters stay per-device (passes are uniform,
    # so every shard agrees)
    stv = st[-2]
    stv = stv.at[0].set(_psum(stv[0], row_axes))
    return st[-1], st[1], stv.at[3].set(_psum(stv[3], row_axes))


class RoundsTreeLearner:
    """Single- or data-parallel learner using batched-rounds growth."""

    def __init__(self, dataset: Dataset, config: Config,
                 mesh: Optional[jax.sharding.Mesh] = None):
        self.dataset = dataset
        self.config = config
        self.mesh = mesh
        self.full_leaf_id = True
        self.N = dataset.num_data
        self.F = dataset.num_features
        self.B = padded_bin_count(dataset.max_num_bin)
        if mesh is not None:
            axes = mesh_axes(mesh)
        else:
            axes = {}
        self.dd = int(axes.get("data", 1))
        # 2-D (data x feature) mesh: rows shard over BOTH axes and the
        # psum_scatter exchange scatters store columns over the feature
        # axis (docs/Distributed-Data.md); nsh is the total row-shard
        # count, nd_sc the scatter world the column padding must tile
        self.df = int(axes.get("feature", 1))
        nsh = self.dd * self.df
        self._nd_sc = self.df if self.df > 1 else self.dd
        self.mh = None
        if mesh is not None and jax.process_count() > 1:
            from ..sharded.mesh import MultiHostRows
            self.mh = MultiHostRows(mesh, self.N)
            self.Np = self.mh.np_global
            self._local_np = self.mh.per_proc
        else:
            self.Np = int(nsh * math.ceil(self.N / max(nsh, 1)))
            self._local_np = self.Np

        backend = ("pallas" if jax.default_backend() == "tpu" else "xla")
        nbv = dataset.num_bins.astype(np.int32)      # ORIGINAL [F]
        icv = np.asarray(dataset.is_categorical)     # ORIGINAL [F]
        plan = dataset.bundle_plan
        # nonzero-iterating sparse path (docs/Sparse.md): single-process
        # only for now — per-host stream assembly is the multi-host
        # follow-on; the dense fallback below is counted by the
        # dataset's bins property
        self.sparse = dataset.sparse is not None and self.mh is None
        if dataset.sparse is not None and not self.sparse:
            from .. import log
            log.warning("sparse store is not wired for multi-host runs "
                        "yet; materializing the dense store")
        if self.sparse:
            bins_np = None
            self.Cstore = dataset.sparse.num_columns
            self.Fpad = self.Cstore
        else:
            store = dataset.dense_bins(
                site="rounds_feed")                  # [C, N] (bundled: C<F)
            self.Cstore = store.shape[0]
            if backend == "pallas" and dataset.max_num_bin <= 256 \
                    and self._want_int8_bins():
                # int8 HBM layout (value - 128): 4x less device memory and
                # bandwidth than int32 — what fits Expo's 11M x 700 store
                # (7.7 GB vs 30.8 GB) on one v5e chip.  Memory-gated: the
                # G=32 block layout it forces measured ~60% slower than the
                # int32 G=8 layout on wide 255-bin data (Epsilon shape), so
                # narrow storage is chosen only when int32 bins would crowd
                # the device (see _want_int8_bins).
                bins_np = (store.astype(np.int16) - 128).astype(np.int8)
                # pad columns to the int8 kernel's 32-sublane group on the
                # HOST: a device-side pad would briefly hold a second full
                # copy of the bins array.  Padded columns are trivial
                # (1 bin, fmask False) and can never be selected.
                self.Fpad = 32 * int(math.ceil(self.Cstore / 32))
            else:
                bins_np = store.astype(np.int32)
                self.Fpad = self.Cstore
        # data-parallel histogram exchange: resolve the collective from
        # the per-pass payload, then (for psum_scatter) align the store
        # columns so the [K, F, 3, B] histogram tiles the data axis —
        # each device owns an F/ndev store-column slice (the sparse
        # path's REDUCED histogram keeps the dense column layout, so
        # the same alignment applies).  Alignment keeps the int8
        # kernel's 32-sublane grouping.
        K_pass = min(LEAVES_PER_BATCH, int(config.num_leaves))
        self.hist_exchange = resolve_hist_exchange(
            config, ndev=nsh,
            payload_bytes=4.0 * K_pass * self.Fpad * 3 * self.B)
        if self.hist_exchange == "psum_scatter" and nsh > 1:
            self.Fpad = pad_cols_to_ndev(
                self.Fpad, self._nd_sc,
                align=32 if (bins_np is not None
                             and bins_np.dtype == np.int8) else 1)
        if self.sparse:
            sps = dataset.sparse
            cols_np = sps.cols.astype(np.int32)
            ell_np = sps.bins.astype(np.int32)
            # the empty-slot sentinel must sit PAST the padded columns,
            # or scatter-aligned padding columns would accumulate
            cols_np = np.where(cols_np >= self.Cstore,
                               np.int32(self.Fpad), cols_np)
            zb_np = np.full(self.Fpad, -1, np.int32)
            zb_np[: self.Cstore] = sps.zero_bin
            if self._local_np > self.N:
                rp = self._local_np - self.N
                cols_np = np.pad(cols_np, ((0, rp), (0, 0)),
                                 constant_values=self.Fpad)
                ell_np = np.pad(ell_np, ((0, rp), (0, 0)))
            self._nnz = int(sps.nnz)
            streams = self._build_sparse_streams(cols_np, ell_np, nsh,
                                                 backend)
        else:
            # pad value must be an in-range bin; padded rows/features
            # carry zero mask so their bin never matters
            pad_val = -128 if bins_np.dtype == np.int8 else 0
            if self.Fpad > self.Cstore:
                fp = self.Fpad - self.Cstore
                bins_np = np.pad(bins_np, ((0, fp), (0, 0)),
                                 constant_values=pad_val)
            if self._local_np > self.N:
                bins_np = np.pad(bins_np,
                                 ((0, 0), (0, self._local_np - self.N)),
                                 constant_values=pad_val)
        if plan is None:
            # unbundled: split metadata mirrors the (padded) store columns
            fp = self.Fpad - self.F
            nbv = np.pad(nbv, (0, fp), constant_values=1)
            icv = np.pad(icv, (0, fp))
            self._base_fmask = np.pad(np.ones(self.F, bool), (0, fp))
            ftbl = None
            unb = None
        else:
            # bundled: histograms unbundle to the ORIGINAL [F] layout
            # before split search, so split metadata keeps original size.
            # The sentinel in the gather tables must account for the
            # int8 layout's 32-aligned column padding (histograms come
            # back [K, Fpad, 3, B]) — a plan-sized sentinel would gather
            # a padded column's bin-0 totals instead of zero
            self._base_fmask = np.ones(self.F, bool)
            ftbl = plan.feat_table()
            unb = dataset.unbundle_tables(self.B, self.Fpad)
        self._row_mask = np.pad(np.ones(self.N, np.float32),
                                (0, self._local_np - self.N))
        self._row_mask_dev = None     # lazy device cache (no bagging path)
        self._fmask_dev = None        # lazy device cache (no sampling path)
        cfg = config
        self.split_kw = make_split_kw(cfg)
        self._feat_rng = np.random.RandomState(cfg.feature_fraction_seed)

        # histogram-memory bound (reference HistogramPool analog); the
        # column count is this shard's local share of the STORE — under
        # psum_scatter each device caches only its F/ndev column slice
        cache_cols = (self.Fpad // self._nd_sc
                      if self.hist_exchange == "psum_scatter" and nsh > 1
                      else self.Fpad)
        self.cache_parent_hist = use_parent_hist_cache(cfg, cache_cols,
                                                       self.B)
        # row feed: gathered (ordered histograms over the device-resident
        # row partition) vs masked full-stream — see build_tree_rounds.
        # Under shard_map the partition is per-shard local state, so the
        # scratch budget is sized from the PER-SHARD row count.  The
        # sparse store defaults to masked (its window entry streams are
        # static store order — every masked pass is already nnz-scaled);
        # explicit gathered composes on the XLA path, where the ELL row
        # segments gather exactly like dense rows.
        if self.sparse:
            hr = getattr(cfg, "hist_rows", "auto")
            if hr == "gathered" and backend == "pallas":
                from .. import log
                log.warning("hist_rows=gathered over the sparse store "
                            "runs the XLA scatter path; using masked "
                            "on TPU")
                hr = "masked"
            self.hist_rows = "masked" if hr == "auto" else hr
        else:
            self.hist_rows = resolve_hist_rows(
                cfg, backend=backend,
                num_columns=self.Fpad,
                np_rows=max(1, self.Np // max(nsh, 1)),
                bins_itemsize=int(bins_np.dtype.itemsize))
        kw = dict(num_leaves=cfg.num_leaves, num_bins_padded=self.B,
                  max_num_bin=int(dataset.max_num_bin),
                  split_kw=self.split_kw, max_depth=int(cfg.max_depth),
                  min_data_in_leaf=int(cfg.min_data_in_leaf),
                  min_sum_hessian_in_leaf=float(cfg.min_sum_hessian_in_leaf),
                  backend=backend,
                  cache_parent_hist=self.cache_parent_hist,
                  hist_rows=self.hist_rows,
                  hist_exchange=self.hist_exchange,
                  num_devices=self.dd,
                  num_feature_shards=self.df,
                  ftbl=ftbl, unb=unb, sparse=self.sparse,
                  input_dtype=getattr(cfg, "histogram_dtype", "float32"))
        if mesh is None:
            self._build = jax.jit(functools.partial(build_tree_rounds, **kw))
            if self.sparse:
                self.bins_dev = ((jnp.asarray(cols_np),
                                  jnp.asarray(ell_np), jnp.asarray(zb_np))
                                 + tuple(jnp.asarray(s) for s in streams))
            else:
                self.bins_dev = jnp.asarray(bins_np)
        else:
            from jax.sharding import PartitionSpec as P, NamedSharding
            from ..sharded.mesh import compat_shard_map, row_shard_axes
            fn = functools.partial(
                build_tree_rounds, **kw,
                data_axis="data" if self.dd > 1 else None,
                feature_axis="feature" if self.df > 1 else None)
            # rows shard over every mesh axis present (the 2-D mesh
            # splits the row axis dd*df ways; store columns replicate).
            # Sparse: ELL rows and the stacked stream blocks shard by
            # rows; zero_bin replicates like the split metadata.
            da = row_shard_axes(self.dd, self.df)
            bins_spec = ((P(da), P(da), P(), P(da), P(da), P(da), P(da))
                         if self.sparse else P(None, da))
            in_specs = (bins_spec, P(da), P(da), P(da), P(), P(), P())
            out_specs = (jax.tree_util.tree_map(lambda _: P(), TreeArrays(
                *[0] * len(TreeArrays._fields))), P(da), P())
            self._build = jax.jit(compat_shard_map(
                fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                check_vma=False))
            if self.mh is not None:
                self.bins_dev = self.mh.put_rows(bins_np, P(None, da))
            elif self.sparse:
                def put(a, spec):
                    return jax.device_put(jnp.asarray(a),
                                          NamedSharding(mesh, spec))
                self.bins_dev = ((put(cols_np, P(da)), put(ell_np, P(da)),
                                  put(zb_np, P()))
                                 + tuple(put(s, P(da)) for s in streams))
            else:
                self.bins_dev = jax.device_put(
                    jnp.asarray(bins_np), NamedSharding(mesh, P(None, da)))
        # replicated metadata stays host numpy in multi-process mode
        # (nbv/icv already carry the int8 feature padding)
        self.num_bins_dev = nbv if self.mh is not None else jnp.asarray(nbv)
        self.is_cat_dev = icv if self.mh is not None else jnp.asarray(icv)

    def _build_sparse_streams(self, cols_np: np.ndarray,
                              ell_np: np.ndarray, nsh: int, backend: str):
        """Stacked per-shard window entry streams for the pallas sparse
        kernel ([nsh, nwin, Ew], every shard padded to the common Ew so
        the stacked leaves shard cleanly).  Off-TPU the XLA path
        iterates the ELL arrays directly, so empty placeholders keep
        the pytree structure without the host sort."""
        from ..ops.histogram import FEATURE_GROUP
        if backend != "pallas":
            z = np.zeros((nsh, 0, 0), np.int32)
            return (z, z.copy(), np.zeros((nsh, 0, 0), np.float32),
                    np.zeros((nsh, 0), np.int32))
        blocks = np.split(np.arange(cols_np.shape[0]), nsh)
        parts = [sparse_window_streams(cols_np[b], ell_np[b], self.Fpad,
                                       num_bins_padded=self.B)
                 for b in blocks]
        # pad every shard to the common window count (padding windows
        # hold sentinel slots/entries and accumulate nothing)
        nwin = max(p[0].shape[0] for p in parts)
        sent = FEATURE_GROUP * self.B
        out_r, out_f, out_v, out_s = [], [], [], []
        for er, ef, ev, sc in parts:
            pad = ((0, nwin - er.shape[0]), (0, 0))
            out_r.append(np.pad(er, pad))
            out_f.append(np.pad(ef, pad, constant_values=sent))
            out_v.append(np.pad(ev, pad))
            out_s.append(np.pad(sc, (0, nwin * FEATURE_GROUP - sc.size),
                                constant_values=self.Fpad))
        return (np.stack(out_r), np.stack(out_f), np.stack(out_v),
                np.stack(out_s))

    def _want_int8_bins(self) -> bool:
        """Narrow bin storage only under memory pressure: int32 bins
        beyond ~25% of device HBM (Expo-scale) switch to the int8
        value-128 layout; narrow/regular data keeps the faster int32
        G=8 kernel layout.  LGBT_BINS_INT8=0/1 overrides for on-chip
        experiments."""
        import os
        ov = os.environ.get("LGBT_BINS_INT8", "")
        if ov in ("0", "1"):
            return ov == "1"
        # bins shard along the data axis: the pressure that matters is
        # the PER-DEVICE share of the int32 STORE layout
        int32_bytes = 4.0 * self.Cstore * self.Np / max(self.dd * self.df, 1)
        try:
            stats = jax.local_devices()[0].memory_stats()
            limit = float(stats.get("bytes_limit", 0)) or 16e9
        except Exception:
            limit = 16e9
        return int32_bytes > 0.25 * limit

    @property
    def bins_t(self):
        """Store view for the ScoreUpdater's binned traversal: the
        sparse ELL triple when the dataset is sparse (the training-set
        replay probes row segments, zero densification), else the
        [N+1, C] sentinel-padded dense transpose."""
        if getattr(self, "_bins_t", None) is None:
            if self.dataset.sparse is not None:
                self._bins_t = self.dataset.sparse_triple()
            else:
                self._bins_t = jnp.asarray(sentinel_bins_t(self.dataset))
        return self._bins_t

    def _feature_mask(self):
        frac = self.config.feature_fraction
        m = self._base_fmask.copy()
        if frac < 1.0:
            # sampling draws from the REAL features; int8-alignment
            # padding features stay masked out
            k = max(1, int(round(self.F * frac)))
            sel = self._feat_rng.choice(self.F, size=k, replace=False)
            mm = np.zeros(len(self._base_fmask), bool)
            mm[sel] = True
            m &= mm
        # per-iteration host draw is the design; the upload is explicit
        return m if self.mh is not None else jax.device_put(m)

    def _pad_rows(self, x: jax.Array):
        if self.mh is not None:
            from jax.sharding import PartitionSpec as P
            return self.mh.put_rows(
                self.mh.pad_local(np.asarray(x, np.float32)), P("data"))
        if self.Np == self.N:
            return x
        return pad_rows_dev(x, pad=self.Np - self.N)

    def _masks(self, bag_idx):
        if self.mh is not None:
            from jax.sharding import PartitionSpec as P
            mask = self._row_mask
            if bag_idx is not None:
                m2 = np.zeros(self._local_np, np.float32)
                bi = np.asarray(bag_idx)
                m2[bi[bi < self.N]] = 1.0
                mask = m2 * mask
            mask = self.mh.put_rows(mask, P("data"))
            fmask = (self._feature_mask()
                     if self.config.feature_fraction < 1.0
                     else self._base_fmask)
            return mask, fmask
        if self._row_mask_dev is None:
            self._row_mask_dev = jax.device_put(self._row_mask)
        mask = self._row_mask_dev
        if bag_idx is not None:
            mask = bag_mask_dev(bag_idx, mask)
        if self.config.feature_fraction < 1.0:
            fmask = self._feature_mask()
        else:
            if self._fmask_dev is None:
                self._fmask_dev = jax.device_put(self._base_fmask)
            fmask = self._fmask_dev
        return mask, fmask

    def train_device(self, grad: jax.Array, hess: jax.Array,
                     bag_idx: Optional[jax.Array] = None,
                     bag_count: Optional[int] = None):
        """Device-only train: (packed tree vector, leaf_id, TreeArrays)
        with NO device→host sync — callers pipeline the tree fetch and can
        score valid sets straight from the device TreeArrays."""
        from .fused import pack_tree_arrays
        from .. import profiling
        mask, fmask = self._masks(bag_idx)
        arrs, leaf_id, stats = self._build(
            self.bins_dev, self._pad_rows(grad), self._pad_rows(hess), mask,
            self.num_bins_dev, self.is_cat_dev, fmask)
        # device scalars, folded into the counters at the next metrics
        # read — no sync on the pipelined path
        self._record_stats(profiling, stats)
        packed = pack_tree_arrays(arrs)
        check_tree_divergence("rounds/tree", arrs, packed)
        return packed, slice_rows_dev(leaf_id, n=self.N), arrs

    def _record_stats(self, profiling, stats) -> None:
        # one jitted unstack: eager stats[i] indexing lowers to
        # dynamic_slice and uploads its start index per iteration
        s0, s1, s2, s3 = unstack_scalars(4)(stats)
        profiling.count_deferred(profiling.HIST_ROWS_TOUCHED, s0)
        profiling.count_deferred(profiling.HIST_EXCHANGE_BYTES, s1)
        profiling.count_deferred(profiling.SPLIT_RECORDS_BYTES, s2)
        profiling.count_deferred(profiling.SPARSE_NNZ_TOUCHED, s3)

    def train(self, grad: jax.Array, hess: jax.Array,
              bag_idx: Optional[jax.Array] = None,
              bag_count: Optional[int] = None) -> Tuple[Tree, jax.Array]:
        from .. import profiling
        mask, fmask = self._masks(bag_idx)
        arrs, leaf_id, stats = self._build(
            self.bins_dev, self._pad_rows(grad), self._pad_rows(hess), mask,
            self.num_bins_dev, self.is_cat_dev, fmask)
        self._record_stats(profiling, stats)
        check_tree_divergence("rounds/tree", arrs)
        tree = tree_arrays_to_host(arrs, self.dataset, self.config.num_leaves)
        if self.mh is not None:
            return tree, jnp.asarray(self.mh.local_rows(leaf_id))
        return tree, slice_rows_dev(leaf_id, n=self.N)
