"""`task=online`: the continuous train-side daemon.

Watches a labeled-traffic JSONL file (the serving `/predict` log joined
with labels — see stream.py), bins each new chunk against FROZEN bin
mappers into a capacity-tiered streaming window, and when
`online_trigger_rows` fresh rows have accumulated, refreshes the model:

- ``online_mode=refit`` (default): reweight the existing tree
  structures' leaves on the window (refit.LeafRefitter — ~one traversal
  plus one scan; the compiled programs persist across refreshes, so the
  loop holds the 0-retrace / 0-implicit-transfer contract);
- ``online_mode=continue``: continued boosting — the existing
  reset_training_data machinery replays the model onto the window's
  scores and `num_iterations` fresh trees are appended.

Each refresh PUBLISHES a new model generation atomically (tmp +
os.replace) to `output_model` — the path a serving ModelRegistry polls
— plus a ``<output_model>.meta.json`` sidecar (generation, mode, rows,
timestamps) that the server surfaces at `/stats` as the `online` block.
The serving fleet hot-swaps the refreshed generation with pre-warmed
buckets and zero recompiles: leaf values changed, shapes did not.

Bin mappers freeze at the FIRST trigger window (or from an explicit
`reference` dataset): every later chunk re-uses them, so no chunk is
ever re-quantized and the stores stay aligned with the trees' rebinned
thresholds.

Crash safety (docs/Robustness.md): the daemon persists a state sidecar
(``<output_model>.state.json``, tmp + os.replace like `_publish`)
holding the traffic byte offset covered by the latest publish, the
generation/refresh counters, the frozen-mapper fingerprint, the traffic
reader's data-loss counters, and the last refresh outcome.  A restarted
daemon resumes from that offset — rows already inside a published
generation are never re-processed, rows of the in-flight window are
re-read from the log and land in exactly one future publish.  Publishes
are guarded by a WRITE-AHEAD INTENT in the sidecar, flushed after the
model is staged but before anything touches the publish path: on
restart, the intent's generation vs the published ``.meta.json`` — and,
for a crash BETWEEN the model and meta renames, the staged model's
recorded sha1 vs what sits at the publish path — decide adopt
(completing the publish from the intent's recorded meta) vs redo.  The frozen bin mappers persist
as a binary dataset sidecar (``<output_model>.refbin``) so a restart
bins against BITWISE the same mappers instead of re-freezing from
whatever window happens to be pending.  SIGTERM drains the current
poll and flushes state before exit.
"""
from __future__ import annotations

import dataclasses
import json
import os
import signal
import threading
import time
from typing import List, Optional, Tuple

import numpy as np

from .. import log, telemetry
from ..config import Config, config_from_params
from ..dataset import Dataset as RawDataset
from ..diagnostics import faults
from ..log import LightGBMError
from .refit import LeafRefitter
from .stream import TrafficDemux, TrafficLog

STATE_VERSION = 1


def _file_sha1(path: str) -> str:
    from ..quantize import file_sha1
    return file_sha1(path)


def _booster_params(cfg: Config) -> dict:
    """Config -> Booster params dict (file/task routing keys dropped so
    the loaded booster cannot accidentally re-trigger IO)."""
    p = dataclasses.asdict(cfg)
    for k in ("task", "data", "input_model", "output_model", "valid_data",
              "output_result", "is_save_binary_file", "config_file"):
        p.pop(k, None)
    return p


class OnlineTrainer:
    """Traffic-watching refresh daemon (see module docstring)."""

    def __init__(self, booster, traffic_path: str, publish_path: str, *,
                 config: Optional[Config] = None, reference=None,
                 resume: bool = True, model_id: Optional[str] = None,
                 match_unkeyed: Optional[bool] = None, traffic=None):
        cfg = config or config_from_params(booster.params)
        if not booster._gbdt.models:
            raise LightGBMError("task=online needs a trained input model")
        self.cfg = cfg
        self.booster = booster
        # catalog tenant id (multi-tenant serving, docs/serving.md
        # "Multi-tenant catalog"): keys this daemon to its own rows of
        # a SHARED traffic tail and stamps the publish sidecar, so the
        # serving catalog's per-tenant poll picks up exactly this
        # tenant's refreshes.  None = the unkeyed single-tenant daemon.
        self.model_id = model_id
        # pin the traffic row width to the model's feature count so a
        # single malformed-width line can never become the yardstick
        # that rejects the valid rows behind it.  `traffic=` injects a
        # pre-built reader (an OnlineFleet hands each tenant a
        # TrafficDemux view so the shared tail is parsed once).
        if traffic is not None:
            self.traffic = traffic
        else:
            self.traffic = TrafficLog(traffic_path,
                                      expected_features=booster.num_feature(),
                                      model_filter=model_id,
                                      match_unkeyed=match_unkeyed)
        self.publish_path = publish_path
        self.state_path = publish_path + ".state.json"
        self.refbin_path = publish_path + ".refbin"
        self.mode = cfg.online_mode
        self.trigger = int(cfg.online_trigger_rows)
        self.generation = 0
        self.refreshes = 0
        self.rows_seen = 0
        # crash-safety bookkeeping: the byte offset covered by the
        # latest publish (where a restarted daemon resumes reading),
        # the frozen-mapper fingerprint, and the last refresh outcome
        self._published_offset = 0
        self._mapper_fp: Optional[str] = None
        self._last_refresh: Optional[dict] = None
        # window state: raw chunks buffer until the first trigger
        # freezes the bin mappers, then a streaming Dataset takes over
        self._window: Optional[RawDataset] = None
        self._buffer: List[Tuple[np.ndarray, np.ndarray,
                                 Optional[np.ndarray]]] = []
        self._buffered_rows = 0
        self._refitter: Optional[LeafRefitter] = None
        # refit mode routes each ingested chunk through the EXACT
        # raw-feature leaf router while the raw values are still in
        # hand (upstream pred_leaf refit parity — the window's binned
        # store quantizes thresholds that fall inside its bins);
        # structures are frozen in refit mode, so routing never stales
        self._leaf_chunks: List[np.ndarray] = []
        # serve→train trace propagation: trace ids stamped into the
        # traffic log by the serving side accumulate per window (capped
        # — provenance, not a ledger) and ride into the publish sidecar
        # as `origin_trace_ids`, independent of whether THIS process
        # has span tracing on
        self._window_traces: set = set()
        self._WINDOW_TRACES_CAP = 1024
        # adaptive bin budgets (bin_budget > 0): each window's raw rows
        # ride in a ring so the post-refresh drift check can recompute
        # the per-feature allocation and refreeze the mappers when the
        # traffic distribution has moved (docs/Online-Learning.md "Adaptive bin
        # budgets"); the baseline allocation re-derives from the first
        # window after every (re)start
        self._rebudget = int(getattr(cfg, "bin_budget", 0) or 0) > 0
        self._raw_ring: List[Tuple[np.ndarray, np.ndarray,
                                   Optional[np.ndarray]]] = []
        self._raw_rows = 0
        self._budget_alloc: Optional[np.ndarray] = None
        if reference is not None:
            self._window = RawDataset.streaming_from(
                reference, cfg, capacity=self.trigger)
        if resume:
            self._try_resume()
        if self._window is None:
            self._adopt_input_refbin()

    @classmethod
    def from_config(cls, cfg: Config) -> "OnlineTrainer":
        from ..basic import Booster
        if not cfg.input_model:
            raise LightGBMError("task=online needs input_model=<file>")
        if not cfg.data:
            raise LightGBMError(
                "task=online needs data=<labeled traffic .jsonl>")
        if not cfg.output_model:
            raise LightGBMError("task=online needs output_model=<registry "
                                "path the serving fleet polls>")
        booster = Booster(params=_booster_params(cfg),
                          model_file=cfg.input_model)
        return cls(booster, cfg.data, cfg.output_model, config=cfg)

    # -- crash-safe state (docs/Robustness.md) --------------------------

    def _state_dict(self, intent: Optional[dict] = None) -> dict:
        st = {
            "version": STATE_VERSION,
            "generation": self.generation,
            "refreshes": self.refreshes,
            "rows_seen": int(self.rows_seen),
            "published_offset": int(self._published_offset),
            "pending_rows": int(self.pending_rows()),
            "mode": self.mode,
            "trigger_rows": self.trigger,
            "mapper_fingerprint": self._mapper_fp,
            "traffic": self.traffic.counters(),
            "last_refresh": self._last_refresh,
            "updated_unix": round(time.time(), 3),
        }
        if intent is not None:
            st["publish_intent"] = intent
        return st

    def _flush_state(self, intent: Optional[dict] = None) -> None:
        """Persist the daemon state sidecar (tmp + os.replace — the
        same atomicity discipline as `_publish`)."""
        payload = json.dumps(self._state_dict(intent))
        faults.torn_write("online.state_write", self.state_path, payload)
        tmp = self.state_path + ".tmp"
        with open(tmp, "w") as f:
            f.write(payload)
        os.replace(tmp, self.state_path)

    def _try_resume(self) -> None:
        """Adopt a previous daemon's persisted state: traffic offset,
        generation counters, published model, frozen bin mappers.  A
        torn/unreadable sidecar logs a warning and starts fresh — a
        crash artifact must never wedge the restart."""
        try:
            with open(self.state_path) as f:
                st = json.load(f)
        except FileNotFoundError:
            return                        # first run: no sidecar yet
        except OSError as e:
            # an existing-but-unreadable sidecar (EACCES/EIO) silently
            # treated as a first run would reset the traffic offset to 0
            # and double-process every published row
            log.warning(f"online: could not read state sidecar "
                        f"{self.state_path} ({type(e).__name__}: {e}); "
                        "starting fresh (traffic re-reads from offset 0)")
            return
        except ValueError as e:
            log.warning(f"online: ignoring unreadable state sidecar "
                        f"{self.state_path} ({type(e).__name__}: {e}); "
                        "starting fresh (traffic re-reads from offset 0)")
            return
        if not isinstance(st, dict) or st.get("version") != STATE_VERSION:
            log.warning(f"online: ignoring incompatible state sidecar "
                        f"{self.state_path}; starting fresh")
            return
        offset = int(st.get("published_offset", 0))
        self.generation = int(st.get("generation", 0))
        self.refreshes = int(st.get("refreshes", 0))
        self.rows_seen = int(st.get("rows_seen", 0))
        self._last_refresh = st.get("last_refresh")
        # publish-intent recovery: a crash BETWEEN the model rename and
        # the state flush left the sidecar one publish behind.  The
        # published .meta.json tells which side of the rename the crash
        # fell on: landed -> adopt the intent (those rows are in the
        # model; re-processing them would double-refit), not landed ->
        # redo the window from the pre-intent offset.
        intent = st.get("publish_intent")
        if intent:
            meta = self._read_meta()
            landed = (meta is not None and
                      meta.get("generation") == intent.get("generation"))
            if not landed:
                # the meta rename is the SECOND rename — the model may
                # already have landed (crash between the two).  The
                # intent's staged-model sha1 decides: if that is what
                # sits at publish_path, COMPLETE the publish by staging
                # the meta recorded in the intent; re-refitting the
                # window would double-apply its rows to the new model.
                sha = intent.get("model_sha1")
                try:
                    if (sha and os.path.exists(self.publish_path)
                            and _file_sha1(self.publish_path) == sha):
                        if intent.get("meta") is not None:
                            mtmp = self.publish_path + ".meta.json.tmp"
                            with open(mtmp, "w") as f:
                                json.dump(intent["meta"], f)
                            os.replace(mtmp,
                                       self.publish_path + ".meta.json")
                        landed = True
                        log.info("online: completed interrupted publish "
                                 f"generation {intent.get('generation')} "
                                 "(crash fell between the model and "
                                 "meta renames)")
                except OSError as e:
                    log.warning("online: could not verify an interrupted "
                                f"publish ({type(e).__name__}: {e}); "
                                "redoing the window")
            if landed:
                self.generation = int(intent["generation"])
                self.refreshes = int(intent.get("refreshes",
                                                self.refreshes + 1))
                self.rows_seen = int(intent.get("rows_seen",
                                                self.rows_seen))
                offset = int(intent.get("offset", offset))
                log.info(f"online: adopted in-flight publish generation "
                         f"{self.generation} (crash fell after the model "
                         "rename, before the state flush)")
            else:
                log.info("online: discarding unfinished publish intent "
                         f"(generation {intent.get('generation')} never "
                         "landed); its window re-reads from the log")
        self._published_offset = offset
        # counters ride along: the sidecar's bad_lines/overcap_skips are
        # the operator's silent-data-loss evidence and must survive the
        # restart, not reset to 0
        self.traffic.seek(offset, st.get("traffic"))
        # continue refreshing the PUBLISHED model (the one the fleet is
        # serving), not the stale input model
        if self.generation > 0 and os.path.exists(self.publish_path):
            from ..basic import Booster
            try:
                self.booster = Booster(params=_booster_params(self.cfg),
                                       model_file=self.publish_path)
            except Exception as e:
                log.warning(f"online: could not reload published model "
                            f"{self.publish_path} ({type(e).__name__}: "
                            f"{e}); continuing from the input model")
        # frozen mappers: rebuild the streaming window from the refbin
        # sidecar so restarted binning is bitwise the original run's
        if self._window is None and os.path.exists(self.refbin_path):
            fp = st.get("mapper_fingerprint")
            try:
                actual = _file_sha1(self.refbin_path)
                if fp is not None and actual != fp:
                    raise ValueError(
                        f"fingerprint {actual[:12]} != recorded "
                        f"{str(fp)[:12]} (torn write?)")
                ref = RawDataset.from_binary(self.refbin_path, self.cfg)
                self._window = RawDataset.streaming_from(
                    ref, self.cfg, capacity=self.trigger)
                self._mapper_fp = actual
            except Exception as e:
                log.warning(f"online: could not restore frozen mappers "
                            f"from {self.refbin_path} ({type(e).__name__}"
                            f": {e}); re-freezing from the next window")
        log.info(f"online: resumed from {self.state_path} — generation "
                 f"{self.generation}, traffic offset {offset}, "
                 f"{self.rows_seen} rows seen")

    def _read_meta(self) -> Optional[dict]:
        try:
            with open(self.publish_path + ".meta.json") as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def _save_refbin(self, base: RawDataset) -> None:
        """Persist the frozen-mapper reference (atomic), so a restarted
        daemon bins against the SAME mappers instead of re-freezing."""
        tmp = self.refbin_path + ".tmp"
        base.save_binary(tmp)
        os.replace(tmp, self.refbin_path)
        self._mapper_fp = _file_sha1(self.refbin_path)

    def _adopt_input_refbin(self) -> None:
        """Freeze the INPUT model's own training mappers when it ships
        a ``.refbin`` sidecar (Dataset.save_refbin at train time).
        Ingestion then bins against the exact mapper set the model's
        thresholds live in, so the published ``<output>.refbin`` stays
        SERVING-exact across refit generations — the binned request
        path (serve_quantize=binned) requires thresholds to BE bin
        boundaries of the sidecar's mappers — and the binned refit
        router becomes exact as a bonus.  Without a sidecar the first
        full window freezes its own mappers, as before (such
        generations serve raw under serve_quantize=auto: the serving
        registry's representability check refuses them)."""
        ip = str(getattr(self.cfg, "input_model", "") or "")
        if not ip or not os.path.exists(ip + ".refbin"):
            return
        from ..quantize import load_refbin
        try:
            ref = load_refbin(ip + ".refbin")
            if ref.num_total_features != self.booster.num_feature():
                raise LightGBMError(
                    f"sidecar covers {ref.num_total_features} features, "
                    f"model has {self.booster.num_feature()}")
            self._window = RawDataset.streaming_from(
                ref, self.cfg, capacity=self.trigger)
            self._save_refbin(ref)
            log.info(f"online: adopted frozen mappers from {ip}.refbin "
                     f"({ref.num_features} used features) — published "
                     "generations stay binned-serving exact")
        except Exception as e:
            self._window = None
            log.warning(f"online: could not adopt {ip}.refbin "
                        f"({type(e).__name__}: {e}); the first "
                        f"{self.trigger}-row window will freeze its own "
                        "mappers")

    # -- ingestion ------------------------------------------------------

    def pending_rows(self) -> int:
        return (self._window.num_data if self._window is not None
                else self._buffered_rows)

    def _ingest(self, X: np.ndarray, y: np.ndarray,
                w: Optional[np.ndarray]) -> None:
        self.rows_seen += len(X)
        if self._rebudget:
            # raw-row ring for the adaptive-budget drift check; capped
            # at 4 windows so a poll backlog cannot grow it unbounded
            self._raw_ring.append((X, y, w))
            self._raw_rows += len(X)
            while (len(self._raw_ring) > 1
                   and self._raw_rows - len(self._raw_ring[0][0])
                   >= 4 * self.trigger):
                self._raw_rows -= len(self._raw_ring.pop(0)[0])
        if self._window is not None:
            self._window.append_rows(X, y, w)
            if self.mode == "refit":
                self._leaf_chunks.append(
                    self.booster._gbdt.predict_leaf_index(X))
            return
        self._buffer.append((X, y, w))
        self._buffered_rows += len(X)
        if self._buffered_rows < self.trigger:
            return
        # first full window: freeze the bin mappers + bundle plan here;
        # every later chunk bins against them (no re-quantization).
        # Construction routes through Dataset.from_stream — the shared
        # out-of-core ingestion path (sharded/ingest.py): a sketch pass
        # over the buffered chunks derives the mappers (exact at window
        # sizes, bitwise what batch construction would freeze), then
        # each chunk bins straight into the capacity-tiered window —
        # the buffer is never concatenated into one monolithic raw
        # matrix.
        rows = self._buffered_rows
        self._window = RawDataset.from_stream(
            list(self._buffer), self.cfg,
            capacity=max(self.trigger, rows))
        if self.mode == "refit":
            # exact raw-feature routing per buffered chunk, while the
            # raw values are still in hand
            for bx, _by, _bw in self._buffer:
                self._leaf_chunks.append(
                    self.booster._gbdt.predict_leaf_index(bx))
        self._buffer = []
        self._buffered_rows = 0
        # the frozen mappers outlive this process: a restarted daemon
        # restores them from the sidecar instead of re-freezing from
        # whatever window happens to be pending at restart time
        try:
            self._save_refbin(self._window.compacted())
        except OSError as e:
            log.warning(f"online: could not persist frozen mappers to "
                        f"{self.refbin_path} ({type(e).__name__}: {e}); "
                        "a restart would re-freeze from its first window")
        log.info(f"online: froze bin mappers from the first "
                 f"{rows}-row window "
                 f"({self._window.num_features} used features, "
                 f"store capacity {self._window.row_capacity})")

    # -- the loop -------------------------------------------------------

    def poll_once(self) -> bool:
        """Ingest any new traffic; refresh + publish when the trigger
        fires.  Returns True iff a new generation was published."""
        got = self.traffic.read_new()
        if got is not None:
            # originating trace ids of the rows just ingested (the
            # serving side stamped them into the log) become window
            # provenance for the next publish.  The cap is enforced
            # per-id: one backlog poll can carry hundreds of thousands
            # of distinct ids, and the whole set lands in the meta
            # sidecar AND the write-ahead intent — provenance, not a
            # ledger, so the first CAP ids win
            for t in self.traffic.last_trace_ids:
                if len(self._window_traces) >= self._WINDOW_TRACES_CAP:
                    break
                if t is not None:
                    self._window_traces.add(t)
            self._ingest(*got)
        if self._window is None or self._window.num_data < self.trigger:
            return False
        return self.refresh()

    def refresh(self) -> bool:
        """Refresh the model on the current window (regardless of the
        trigger), publish the new generation, reset the window."""
        window = self._window
        if window is None or window.num_data == 0:
            return False
        # ONE trace id spans the whole refresh — refit/continue,
        # publish, and (via the meta sidecar) the serving registry's
        # hot-swap adopt it, so the train half of the serve→train→serve
        # loop is a single grep (per tenant: the model attr keys it)
        with telemetry.span("online.refresh", mode=self.mode,
                            rows=int(window.num_data),
                            generation=self.generation + 1,
                            origin_traces=len(self._window_traces),
                            **({"model": self.model_id}
                               if self.model_id is not None else {})):
            t0 = time.perf_counter()
            if self.mode == "continue":
                with telemetry.span("online.continue"):
                    stats = self._continue_boosting(window)
            else:
                if self._refitter is None:
                    self._refitter = LeafRefitter(self.booster._gbdt,
                                                  window)
                # exact raw-feature routing accumulated at ingestion;
                # the binned router only backstops a count mismatch
                # (e.g. rows appended to the window behind the
                # trainer's back)
                leaf = (np.concatenate(self._leaf_chunks)
                        if self._leaf_chunks else None)
                if leaf is not None and len(leaf) != window.num_data:
                    leaf = None
                with telemetry.span("online.refit",
                                    rows=int(window.num_data)):
                    stats = self._refitter.refit(leaf_idx=leaf)
            stats["refresh_seconds"] = round(time.perf_counter() - t0, 4)
            self._publish(stats)
        window.reset_rows()
        self._maybe_rebudget()
        self._leaf_chunks = []
        self._window_traces = set()
        self._published_offset = int(self.traffic.offset)
        self._record_refresh(ok=True, rows=stats.get("rows", 0))
        self._flush_state()
        return True

    def _window_budget_alloc(self) -> Optional[np.ndarray]:
        """Per-raw-feature adaptive bin allocation over the ring's raw
        rows — the same distinct/mass rule find_bin_mappers applies
        under ``bin_budget`` (binning.allocate_bin_budgets), so two
        windows from the same distribution produce the same vector and
        drift is measured allocation-vs-allocation, not against the
        mappers' realized bin counts (which find_bin may leave under
        budget on low-cardinality features)."""
        if not self._raw_ring:
            return None
        from ..binning import allocate_bin_budgets
        X = np.concatenate([c[0] for c in self._raw_ring])
        d = np.empty(X.shape[1], np.int64)
        m = np.empty(X.shape[1], np.int64)
        for j in range(X.shape[1]):
            col = X[:, j]
            nz = col[(col != 0.0) & ~np.isnan(col)]
            d[j] = np.unique(nz).size + 1     # + the implied zero
            m[j] = nz.size
        return allocate_bin_budgets(d, m, int(self.cfg.bin_budget))

    def _maybe_rebudget(self) -> None:
        """Adaptive bin budgets under drift (``bin_budget > 0``): after
        each refresh, recompute the per-feature allocation over the
        window just consumed; when it drifts from the baseline
        allocation by more than LIGHTGBM_TPU_ONLINE_REBUDGET_DRIFT
        (L1 share, default 0.25), refreeze the mappers from the ring's
        raw rows through the existing refbin handshake — the sidecar
        sha1 updates, the next publish meta carries it, and
        serve_quantize=auto re-resolves binned vs raw against the new
        boundaries (a registry serving the old generation keeps its old
        refbin until the hot-swap)."""
        if not self._rebudget:
            return
        want = self._window_budget_alloc()
        if want is None:
            return
        base = self._budget_alloc
        if base is None or want.size != base.size:
            self._budget_alloc = want
            self._raw_ring, self._raw_rows = [], 0
            return
        drift = (float(np.abs(want.astype(np.int64)
                              - base.astype(np.int64)).sum())
                 / max(int(base.sum()), 1))
        thresh = float(os.environ.get(
            "LIGHTGBM_TPU_ONLINE_REBUDGET_DRIFT", "0.25"))
        if drift > thresh:
            X = np.concatenate([c[0] for c in self._raw_ring])
            y = np.concatenate([c[1] for c in self._raw_ring])
            try:
                newref = RawDataset(X, y, config=self.cfg)
                self._window = RawDataset.streaming_from(
                    newref, self.cfg, capacity=self.trigger)
                self._save_refbin(newref)
                self._refitter = None     # window dataset changed
                self._budget_alloc = want
                log.info(
                    f"online: bin-budget drift {drift:.3f} > {thresh:g}"
                    f" — refroze adaptive mappers from the last "
                    f"{len(X)}-row window (refbin "
                    f"{str(self._mapper_fp)[:12]})")
            except Exception as e:
                log.warning(
                    f"online: bin-budget refreeze failed "
                    f"({type(e).__name__}: {e}); keeping the frozen "
                    "mappers")
        self._raw_ring, self._raw_rows = [], 0

    def _record_refresh(self, ok: bool, rows: int = 0,
                        error: Optional[str] = None) -> None:
        self._last_refresh = {"ok": bool(ok), "rows": int(rows),
                              "generation": self.generation,
                              "unix": round(time.time(), 3)}
        if error:
            self._last_refresh["error"] = error

    def _continue_boosting(self, window: RawDataset) -> dict:
        """Append num_iterations fresh trees on the window: the existing
        continued-training machinery — reset_training_data replays the
        model onto the window's scores (tensorized binned replay), then
        ordinary boosting iterations grow new trees."""
        g = self.booster._gbdt
        inner = window.compacted()
        before = len(g.models)
        g.reset_training_data(inner, g.objective)
        for _ in range(self.cfg.num_iterations):
            if g.train_one_iter(None, None, False):
                break
        g._flush_pending()
        self._refitter = None      # structure changed
        return {"mode": "continue", "rows": int(inner.num_data),
                "trees_before": before, "trees_after": len(g.models)}

    def _publish(self, stats: dict) -> None:
        """Atomically publish the refreshed model + metadata sidecar.
        os.replace is atomic on one filesystem, so the registry's
        (mtime, size) poll can never observe a half-written model."""
        # the in-memory counters advance only once the publish LANDS:
        # until then the sidecar's top-level state must keep describing
        # the previous generation (a discarded intent on restart adopts
        # the top-level values verbatim)
        gen = self.generation + 1
        tmp = f"{self.publish_path}.g{gen}.tmp"
        self.booster.save_model(tmp)
        meta = {"generation": gen, "mode": self.mode,
                # catalog tenant provenance: which tenant's daemon
                # published this generation (None outside the catalog)
                "model_id": self.model_id,
                "refreshes": self.refreshes + 1,
                "rows_seen": int(self.rows_seen),
                "trigger_rows": self.trigger,
                # silent-data-loss visibility: the traffic reader's
                # skip counters ride into /stats' `online` block
                "traffic": self.traffic.counters(),
                # trace propagation: the refresh's own trace id (the
                # serving registry's hot-swap span adopts it) plus the
                # originating serve-request ids this window was built
                # from — the sidecar is the cross-process hop of the
                # serve→train→serve loop
                "trace_id": telemetry.current_trace_id(),
                "origin_trace_ids": sorted(self._window_traces),
                # frozen-mapper fingerprint: the serving registry
                # refuses a binned hot-swap whose .refbin sidecar does
                # not hash to this (docs/serving.md "Binned inference")
                "refbin_sha1": self._mapper_fp,
                "published_unix": round(time.time(), 3), **stats}
        # write-ahead intent BEFORE anything touches publish_path: a
        # crash anywhere in the rename window is resolved on restart.
        # The staged model's sha1 disambiguates a crash BETWEEN the two
        # renames (model landed, meta did not — the .meta.json generation
        # alone cannot tell that apart from "nothing landed"), and the
        # full meta payload rides along so restart can COMPLETE such an
        # interrupted publish instead of double-refitting the window.
        self._flush_state(intent={
            "generation": gen,
            "refreshes": self.refreshes + 1,
            "rows_seen": int(self.rows_seen),
            "offset": int(self.traffic.offset),
            "model_sha1": _file_sha1(tmp),
            "meta": meta})
        with telemetry.span("online.publish", generation=gen,
                            path=self.publish_path):
            # chaos seams: crash before anything lands / model file
            # torn mid-write at the FINAL path (the no-tmp-discipline
            # failure the registry's poll must survive) —
            # tests/test_faults.py
            faults.check("online.before_publish")
            faults.torn_copy("online.publish_model", tmp,
                             self.publish_path)
            mtmp = f"{self.publish_path}.meta.json.tmp"
            with open(mtmp, "w") as f:
                json.dump(meta, f)
            # both files staged before either lands: the model/sidecar
            # inconsistency window a /stats poll can observe is two
            # back-to-back renames, not a model save + json dump
            os.replace(tmp, self.publish_path)
            # chaos seam: crash with the model landed but the meta not
            # — the case only the intent's model sha1 can disambiguate
            faults.check("online.between_renames")
            os.replace(mtmp, self.publish_path + ".meta.json")
        self.generation = gen
        self.refreshes += 1
        faults.check("online.after_publish")
        log.info(f"online: published generation {self.generation} "
                 f"({self.mode}, {stats.get('rows', 0)} rows) to "
                 f"{self.publish_path}")

    def _guarded_poll(self) -> None:
        """One poll that can never kill the daemon on a bad window —
        except an injected CRASH, which is a crash (no drain, no state
        flush: chaos runs must exercise the cold restart)."""
        try:
            self.poll_once()
        except faults.InjectedFault:
            raise
        except Exception as e:      # never kill the daemon on one window
            self._record_refresh(ok=False,
                                 error=f"{type(e).__name__}: {e}")
            log.warning(f"online refresh failed: {e}")
            try:
                self._flush_state()   # the failure is /stats-visible
            except OSError:
                pass

    def run_forever(self, poll_seconds: Optional[float] = None,
                    stop: Optional[threading.Event] = None) -> None:
        """Blocking poll loop; `stop` lets tests (and signal handlers)
        end it cleanly.  SIGTERM drains: the current poll finishes, one
        final poll ingests whatever already reached the log, and the
        state sidecar flushes so the NEXT daemon resumes exactly here."""
        period = (self.cfg.model_poll_seconds if poll_seconds is None
                  else float(poll_seconds)) or 1.0
        log.info(f"online: watching {self.traffic.path} every "
                 f"{period:g}s (mode={self.mode}, trigger="
                 f"{self.trigger} rows, publishing to "
                 f"{self.publish_path})")

        def flush_all():
            try:
                self._flush_state()
            except OSError as e:
                log.warning(f"online: final state flush failed: {e}")

        _run_daemon_loop(period, stop, self._guarded_poll, flush_all,
                         "online: stopped (state flushed to "
                         f"{self.state_path})")


def _run_daemon_loop(period: float, stop: Optional[threading.Event],
                     poll, flush_all, stopped_msg: str) -> None:
    """The poll/drain/flush lifecycle shared by the single daemon and
    the fleet: SIGTERM (main thread only; tests pass `stop`) ends the
    loop, ONE final drain poll ingests whatever already reached the
    log — an InjectedFault during it propagates WITHOUT the final
    flush (chaos runs exercise the cold restart) — then every state
    sidecar flushes so the next daemon resumes exactly here."""
    stop = stop or threading.Event()
    try:
        signal.signal(signal.SIGTERM, lambda *_: stop.set())
    except (ValueError, OSError):
        pass
    while not stop.wait(period):
        poll()
    try:                            # drain: SIGTERM/stop arrived
        poll()
    except faults.InjectedFault:
        raise
    flush_all()
    log.info(stopped_msg)


class OnlineFleet:
    """One `OnlineTrainer` per catalog tenant, sharing ONE traffic tail.

    `serve_models` (the same ``id=path`` entries the serving catalog
    uses) drives ``task=online`` into fleet mode: each tenant's daemon
    tails the SAME labeled-traffic file but ingests only its own keyed
    rows (TrafficLog ``model_filter``; unkeyed rows feed the
    ``default`` entry, or the first entry when none is named
    ``default``), refreshes the model AT its tenant's path, and
    publishes back to that path — which is exactly what the serving
    catalog polls per tenant.  State/refbin sidecars key off each
    publish path, so crash-safe resume stays per-tenant.  One tenant's
    refresh failure never stalls the others.

    The shared tail is read and parsed ONCE per poll cycle: the fleet
    builds a single `TrafficDemux` over the traffic file and hands each
    tenant's daemon a per-tenant view (same TrafficLog surface, so
    crash-safe offset resume is unchanged).  Poll cost scales with log
    bytes, not tenants x log bytes.
    """

    def __init__(self, trainers: List[OnlineTrainer]):
        if not trainers:
            raise LightGBMError("OnlineFleet needs at least one trainer")
        self.trainers = list(trainers)

    @classmethod
    def from_config(cls, cfg: Config) -> "OnlineFleet":
        from ..basic import Booster
        from ..serving.server import catalog_models_from_config
        if not cfg.data:
            raise LightGBMError(
                "task=online needs data=<labeled traffic .jsonl>")
        # the SAME id→path map the serving catalog builds — including
        # `input_model` as the `default` tenant: the serving side keys
        # unnamed requests (and their traffic rows) "default", so a
        # fleet without that daemon would silently filter every
        # default-keyed row and let the default model go stale
        models = catalog_models_from_config(cfg)
        unkeyed_owner = ("default" if "default" in models
                         else next(iter(models)))
        # ONE tailer for the whole fleet: each tenant gets a demux view
        # instead of an independent TrafficLog, so the shared file is
        # read and JSON-parsed once per poll cycle
        demux = TrafficDemux(cfg.data)
        trainers = []
        for mid, path in models.items():
            # each tenant's model path is both the daemon's input and
            # its publish target: the daemon refreshes the published
            # file in place (atomic os.replace), the catalog's
            # per-tenant poll picks it up
            tcfg = cfg.with_updates(input_model=path, output_model=path)
            booster = Booster(params=_booster_params(tcfg),
                              model_file=path)
            trainers.append(OnlineTrainer(
                booster, cfg.data, path, config=tcfg, model_id=mid,
                match_unkeyed=(mid == unkeyed_owner),
                traffic=demux.view(
                    model_filter=mid,
                    match_unkeyed=(mid == unkeyed_owner),
                    expected_features=booster.num_feature())))
        log.info(f"online fleet: {len(trainers)} tenant daemons "
                 f"({', '.join(models)}) sharing {cfg.data}")
        return cls(trainers)

    def pending_rows(self) -> int:
        return sum(t.pending_rows() for t in self.trainers)

    def poll_once(self) -> int:
        """Poll every tenant once; returns generations published."""
        published = 0
        for t in self.trainers:
            try:
                if t.poll_once():
                    published += 1
            except faults.InjectedFault:
                raise               # chaos runs exercise the cold restart
            except Exception as e:  # isolate: tenant A's bad window
                # must not stall tenant B's refreshes
                t._record_refresh(ok=False,
                                  error=f"{type(e).__name__}: {e}")
                log.warning(f"online refresh failed for "
                            f"{t.model_id}: {e}")
                try:
                    t._flush_state()
                except OSError:
                    pass
        return published

    def run_forever(self, poll_seconds: Optional[float] = None,
                    stop: Optional[threading.Event] = None) -> None:
        """Blocking fleet loop — the multi-tenant ``task=online``
        entry; SIGTERM drains every tenant and flushes every state
        sidecar (the same `_run_daemon_loop` discipline as the single
        daemon; per-tenant failures are already isolated in
        poll_once)."""
        period = (self.cfg_poll if poll_seconds is None
                  else float(poll_seconds)) or 1.0

        def flush_all():
            for t in self.trainers:
                try:
                    t._flush_state()
                except OSError as e:
                    log.warning(f"online fleet: state flush failed for "
                                f"{t.model_id}: {e}")

        _run_daemon_loop(period, stop, self.poll_once, flush_all,
                         "online fleet: stopped")

    @property
    def cfg_poll(self) -> float:
        return self.trainers[0].cfg.model_poll_seconds
