"""`task=online`: the continuous train-side daemon.

Watches a labeled-traffic JSONL file (the serving `/predict` log joined
with labels — see stream.py), bins each new chunk against FROZEN bin
mappers into a capacity-tiered streaming window, and when
`online_trigger_rows` fresh rows have accumulated, refreshes the model:

- ``online_mode=refit`` (default): reweight the existing tree
  structures' leaves on the window (refit.LeafRefitter — ~one traversal
  plus one scan; the compiled programs persist across refreshes, so the
  loop holds the 0-retrace / 0-implicit-transfer contract);
- ``online_mode=continue``: continued boosting — the existing
  reset_training_data machinery replays the model onto the window's
  scores and `num_iterations` fresh trees are appended.

Each refresh PUBLISHES a new model generation atomically (tmp +
os.replace) to `output_model` — the path a serving ModelRegistry polls
— plus a ``<output_model>.meta.json`` sidecar (generation, mode, rows,
timestamps) that the server surfaces at `/stats` as the `online` block.
The serving fleet hot-swaps the refreshed generation with pre-warmed
buckets and zero recompiles: leaf values changed, shapes did not.

Bin mappers freeze at the FIRST trigger window (or from an explicit
`reference` dataset): every later chunk re-uses them, so no chunk is
ever re-quantized and the stores stay aligned with the trees' rebinned
thresholds.
"""
from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
from typing import List, Optional, Tuple

import numpy as np

from .. import log
from ..config import Config, config_from_params
from ..dataset import Dataset as RawDataset
from ..log import LightGBMError
from .refit import LeafRefitter
from .stream import TrafficLog


def _booster_params(cfg: Config) -> dict:
    """Config -> Booster params dict (file/task routing keys dropped so
    the loaded booster cannot accidentally re-trigger IO)."""
    p = dataclasses.asdict(cfg)
    for k in ("task", "data", "input_model", "output_model", "valid_data",
              "output_result", "is_save_binary_file", "config_file"):
        p.pop(k, None)
    return p


class OnlineTrainer:
    """Traffic-watching refresh daemon (see module docstring)."""

    def __init__(self, booster, traffic_path: str, publish_path: str, *,
                 config: Optional[Config] = None, reference=None):
        cfg = config or config_from_params(booster.params)
        if not booster._gbdt.models:
            raise LightGBMError("task=online needs a trained input model")
        self.cfg = cfg
        self.booster = booster
        # pin the traffic row width to the model's feature count so a
        # single malformed-width line can never become the yardstick
        # that rejects the valid rows behind it
        self.traffic = TrafficLog(traffic_path,
                                  expected_features=booster.num_feature())
        self.publish_path = publish_path
        self.mode = cfg.online_mode
        self.trigger = int(cfg.online_trigger_rows)
        self.generation = 0
        self.refreshes = 0
        self.rows_seen = 0
        # window state: raw chunks buffer until the first trigger
        # freezes the bin mappers, then a streaming Dataset takes over
        self._window: Optional[RawDataset] = None
        self._buffer: List[Tuple[np.ndarray, np.ndarray,
                                 Optional[np.ndarray]]] = []
        self._buffered_rows = 0
        self._refitter: Optional[LeafRefitter] = None
        # refit mode routes each ingested chunk through the EXACT
        # raw-feature leaf router while the raw values are still in
        # hand (upstream pred_leaf refit parity — the window's binned
        # store quantizes thresholds that fall inside its bins);
        # structures are frozen in refit mode, so routing never stales
        self._leaf_chunks: List[np.ndarray] = []
        if reference is not None:
            self._window = RawDataset.streaming_from(
                reference, cfg, capacity=self.trigger)

    @classmethod
    def from_config(cls, cfg: Config) -> "OnlineTrainer":
        from ..basic import Booster
        if not cfg.input_model:
            raise LightGBMError("task=online needs input_model=<file>")
        if not cfg.data:
            raise LightGBMError(
                "task=online needs data=<labeled traffic .jsonl>")
        if not cfg.output_model:
            raise LightGBMError("task=online needs output_model=<registry "
                                "path the serving fleet polls>")
        booster = Booster(params=_booster_params(cfg),
                          model_file=cfg.input_model)
        return cls(booster, cfg.data, cfg.output_model, config=cfg)

    # -- ingestion ------------------------------------------------------

    def pending_rows(self) -> int:
        return (self._window.num_data if self._window is not None
                else self._buffered_rows)

    def _ingest(self, X: np.ndarray, y: np.ndarray,
                w: Optional[np.ndarray]) -> None:
        self.rows_seen += len(X)
        if self._window is not None:
            self._window.append_rows(X, y, w)
            if self.mode == "refit":
                self._leaf_chunks.append(
                    self.booster._gbdt.predict_leaf_index(X))
            return
        self._buffer.append((X, y, w))
        self._buffered_rows += len(X)
        if self._buffered_rows < self.trigger:
            return
        # first full window: freeze the bin mappers + bundle plan here;
        # every later chunk bins against them (no re-quantization)
        Xa = np.concatenate([b[0] for b in self._buffer])
        ya = np.concatenate([b[1] for b in self._buffer])
        wa = (np.concatenate([
            np.ones(len(b[0]), np.float32) if b[2] is None else b[2]
            for b in self._buffer])
            if any(b[2] is not None for b in self._buffer) else None)
        base = RawDataset(Xa, ya, self.cfg)
        self._window = RawDataset.streaming_from(
            base, self.cfg, capacity=max(self.trigger, len(Xa)))
        # `base` already binned these exact rows against the mappers
        # the window just froze — adopt its store instead of re-binning
        # (append_rows produces bitwise-identical bins:
        # tests/test_online.py::test_streaming_append_matches_batch_binning)
        win = self._window
        win.bins[:, : len(Xa)] = base.bins
        win.num_data = len(Xa)
        win.bundle_conflict_rows = base.bundle_conflict_rows
        win.metadata.label = ya.astype(np.float32)
        if wa is not None:
            win.metadata.weights = wa.astype(np.float32)
        win._device_bins = None
        if self.mode == "refit":
            self._leaf_chunks.append(
                self.booster._gbdt.predict_leaf_index(Xa))
        self._buffer = []
        self._buffered_rows = 0
        log.info(f"online: froze bin mappers from the first "
                 f"{len(Xa)}-row window "
                 f"({self._window.num_features} used features, "
                 f"store capacity {self._window.row_capacity})")

    # -- the loop -------------------------------------------------------

    def poll_once(self) -> bool:
        """Ingest any new traffic; refresh + publish when the trigger
        fires.  Returns True iff a new generation was published."""
        got = self.traffic.read_new()
        if got is not None:
            self._ingest(*got)
        if self._window is None or self._window.num_data < self.trigger:
            return False
        return self.refresh()

    def refresh(self) -> bool:
        """Refresh the model on the current window (regardless of the
        trigger), publish the new generation, reset the window."""
        window = self._window
        if window is None or window.num_data == 0:
            return False
        t0 = time.perf_counter()
        if self.mode == "continue":
            stats = self._continue_boosting(window)
        else:
            if self._refitter is None:
                self._refitter = LeafRefitter(self.booster._gbdt, window)
            # exact raw-feature routing accumulated at ingestion; the
            # binned router only backstops a count mismatch (e.g. rows
            # appended to the window behind the trainer's back)
            leaf = (np.concatenate(self._leaf_chunks)
                    if self._leaf_chunks else None)
            if leaf is not None and len(leaf) != window.num_data:
                leaf = None
            stats = self._refitter.refit(leaf_idx=leaf)
        stats["refresh_seconds"] = round(time.perf_counter() - t0, 4)
        self.refreshes += 1
        self._publish(stats)
        window.reset_rows()
        self._leaf_chunks = []
        return True

    def _continue_boosting(self, window: RawDataset) -> dict:
        """Append num_iterations fresh trees on the window: the existing
        continued-training machinery — reset_training_data replays the
        model onto the window's scores (tensorized binned replay), then
        ordinary boosting iterations grow new trees."""
        g = self.booster._gbdt
        inner = window.compacted()
        before = len(g.models)
        g.reset_training_data(inner, g.objective)
        for _ in range(self.cfg.num_iterations):
            if g.train_one_iter(None, None, False):
                break
        g._flush_pending()
        self._refitter = None      # structure changed
        return {"mode": "continue", "rows": int(inner.num_data),
                "trees_before": before, "trees_after": len(g.models)}

    def _publish(self, stats: dict) -> None:
        """Atomically publish the refreshed model + metadata sidecar.
        os.replace is atomic on one filesystem, so the registry's
        (mtime, size) poll can never observe a half-written model."""
        self.generation += 1
        tmp = f"{self.publish_path}.g{self.generation}.tmp"
        self.booster.save_model(tmp)
        meta = {"generation": self.generation, "mode": self.mode,
                "refreshes": self.refreshes,
                "rows_seen": int(self.rows_seen),
                "trigger_rows": self.trigger,
                "published_unix": round(time.time(), 3), **stats}
        mtmp = f"{self.publish_path}.meta.json.tmp"
        with open(mtmp, "w") as f:
            json.dump(meta, f)
        # both files staged before either lands: the model/sidecar
        # inconsistency window a /stats poll can observe is two
        # back-to-back renames, not a model save + json dump
        os.replace(tmp, self.publish_path)
        os.replace(mtmp, self.publish_path + ".meta.json")
        log.info(f"online: published generation {self.generation} "
                 f"({self.mode}, {stats.get('rows', 0)} rows) to "
                 f"{self.publish_path}")

    def run_forever(self, poll_seconds: Optional[float] = None,
                    stop: Optional[threading.Event] = None) -> None:
        """Blocking poll loop; `stop` lets tests (and signal handlers)
        end it cleanly."""
        period = (self.cfg.model_poll_seconds if poll_seconds is None
                  else float(poll_seconds)) or 1.0
        stop = stop or threading.Event()
        log.info(f"online: watching {self.traffic.path} every "
                 f"{period:g}s (mode={self.mode}, trigger="
                 f"{self.trigger} rows, publishing to "
                 f"{self.publish_path})")
        while not stop.wait(period):
            try:
                self.poll_once()
            except Exception as e:   # never kill the daemon on one window
                log.warning(f"online refresh failed: {e}")
