"""Leaf-value refit from fresh labeled data.

Reference semantics (`GBDT::RefitTree` + `SerialTreeLearner::
FitByExistingTree`): tree STRUCTURES are kept, leaf VALUES are re-fit
on new labels — per boosting iteration, gradients are taken at the
scores of the already-refitted trees, each leaf gets the Newton output
of the rows routed to it, and the result blends with the old value:

    new = refit_decay_rate * old
        + (1 - refit_decay_rate) * clip(leaf_output(sum_g, sum_h,
                                                    l1, l2) * shrinkage,
                                        +-100)

Because routing is FIXED (no tree growth), the reference's sequential
per-iteration loop collapses into two device programs:

1. ONE binned ensemble traversal routes every row through every tree
   (`ops.predict.predict_ensemble_leaf_binned` — `depth` fused passes,
   integer bin compares, EFB remap included): [T, N] leaf indices.
   Callers still holding the raw feature values (Booster.refit, the
   OnlineTrainer ingestion loop, LGBM_BoosterRefit) pass precomputed
   `leaf_idx` from the exact raw-feature router instead — upstream's
   pred_leaf refit semantics, immune to the quantization of routing
   a tree against a store with different bin mappers.
2. ONE jitted `lax.scan` over iterations: each step is the objective's
   elementwise gradient program plus per-leaf sum / count / value
   lookups expressed as one shared one-hot matmul (the package's
   TPU lookup idiom, ops/lookup.py) — no histograms, no split search.

So a refresh costs ~one histogram-pass-equivalent instead of a full
retrain, and refitting on the original training data with
`refit_decay_rate=0` reproduces the original leaf values (bitwise on
dyadic gradients/learning rates; <= 1e-6 otherwise).

Guards: leaves with fewer than `refit_min_rows` fresh rows keep their
old value (a starved leaf's Newton step is noise — and a zero-hessian
leaf would divide by zero), as do FROZEN trees: the boost-from-average
init tree and constant stumps (degenerate-class defaults), whose
values are baselines, not fits.

Steady state holds the PR 5 contract: all host<->device traffic is
explicit (`jax.device_put`/`jax.device_get`), and every compiled shape
keys on the store's CAPACITY TIER (dataset.row_capacity), so repeated
refits over a streaming window never retrace.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..log import LightGBMError

# classes whose jitted gradient program takes integer labels
_INT_LABEL_OBJECTIVES = ("multiclass", "multiclassova")


class LeafRefitter:
    """Reusable refit program for one (model structure, dataset) pair.

    Build once, call :meth:`refit` per refresh window — the routing
    stack, objective gradient program, and the refit scan all compile
    on the first call and are reused while the model structure and the
    store's capacity tier hold (a tier jump recompiles once).
    """

    def __init__(self, gbdt, dataset, *, decay_rate: Optional[float] = None,
                 min_rows: Optional[int] = None):
        cfg = gbdt.config
        gbdt._flush_pending()
        if not gbdt.models:
            raise LightGBMError("cannot refit a model with no trees")
        self.gbdt = gbdt
        self.dataset = dataset
        self.decay = (cfg.refit_decay_rate if decay_rate is None
                      else float(decay_rate))
        if not 0.0 <= self.decay <= 1.0:
            raise ValueError("decay_rate must be in [0, 1]")
        self.min_rows = (cfg.refit_min_rows if min_rows is None
                         else int(min_rows))
        models = gbdt.models
        self.T = len(models)
        self.K = max(int(gbdt.K), 1)
        if self.T % self.K:
            raise LightGBMError(
                f"model has {self.T} trees, not a multiple of "
                f"num_tree_per_iteration={self.K}")
        self.iters = self.T // self.K
        self.M = max(int(t.max_leaves) for t in models)
        # the binned routing stack (tree rebin + device upload) builds
        # lazily on the first refit WITHOUT caller-supplied leaf_idx —
        # Booster.refit / the C API / the OnlineTrainer loop all route
        # raw-exactly and never pay for it
        self._stack = None
        self._meta = None
        self._feat_tbl = None
        frozen = np.zeros(self.T, bool)
        if gbdt.boost_from_average_used and self.T:
            frozen[0] = True
        for i, t in enumerate(models):
            if t.num_leaves < 2:
                frozen[i] = True
        self._frozen = frozen
        self._objective = self._clone_objective(gbdt, dataset)
        self._label_int = self._objective.name in _INT_LABEL_OBJECTIVES
        self._fn = self._build_program(cfg)
        self.refits = 0

    # -- setup ----------------------------------------------------------

    def _ensure_router(self):
        """Build the binned routing stack on first use (refit() with no
        caller-supplied leaf_idx)."""
        if self._stack is not None:
            return
        from ..ops.predict import stack_ensemble
        gbdt, dataset = self.gbdt, self.dataset
        train_set = getattr(gbdt, "train_set", None)
        for t in gbdt.models:
            if dataset is not train_set and not getattr(t, "needs_rebin",
                                                        False):
                # in-session trees carry in-bin thresholds for the
                # TRAINING mappers; against any other store they must
                # re-derive them from the real-valued thresholds.
                # Against the training mappers the recovery is exact
                # (thresholds ARE bin upper bounds); against a store
                # with its own mappers the binned route quantizes a
                # threshold that falls inside a bin — callers holding
                # raw features pass exact raw-routed `leaf_idx`
                # instead and never hit this path
                t.needs_rebin = True
            t.rebin_to_dataset(dataset)
        # model-order routing stack: one "class" per tree, so the
        # class-major flatten IS model order and row t of the [T, N]
        # walk is models[t]
        stack, meta = stack_ensemble([[t] for t in gbdt.models],
                                     binned=True)
        self._stack = jax.device_put(stack)
        self._meta = meta
        ft = dataset.bundle_feat_table()
        self._feat_tbl = None if ft is None else jax.device_put(
            np.asarray(ft))

    @staticmethod
    def _clone_objective(gbdt, dataset):
        """A fresh objective of the model's type, initialized on the
        refit data: init() builds the jitted gradient program and any
        label-derived host constants (binary's is_unbalance weights)
        WITHOUT touching the training objective's state."""
        from ..objectives import create_objective, objective_from_model_string
        base = gbdt.objective
        obj = (objective_from_model_string(base.to_string(), gbdt.config)
               if base is not None else create_objective(gbdt.config))
        if obj.name == "lambdarank":
            raise LightGBMError(
                "leaf refit does not support lambdarank yet (traffic "
                "windows would need whole queries)")
        obj.init(dataset.metadata, dataset.num_data)
        if not hasattr(obj, "_f"):
            raise LightGBMError(
                f"objective {obj.name!r} has no jittable gradient "
                "program; leaf refit cannot trace it")
        return obj

    def _build_program(self, cfg):
        """The jitted refit scan.  All hyperparameters are trace
        constants; everything that changes per refresh window (leaf
        routing, old values, labels, weights, validity) is an array
        argument, so steady-state calls hit the jit cache."""
        from ..ops.split import leaf_output
        obj_f = self._objective._f
        M = self.M
        decay = float(self.decay)
        # a zero-row leaf must never take its (0/0) Newton step
        minr = float(max(self.min_rows, 1))
        l1 = float(cfg.lambda_l1)
        l2 = float(cfg.lambda_l2)

        @jax.jit
        def run(leaf, old_lv, shrink, ok, label, weights, valid, score0):
            # leaf [iters, K, N] i32; old_lv [iters, K, M] f32;
            # shrink/ok [iters, K]; label/weights/valid [N]; score0 [K, N]
            iota = jax.lax.broadcasted_iota(jnp.int32, (1, M, 1), 1)
            P = jax.lax.Precision.HIGHEST

            def body(score, per):
                lf, old, shr, okk = per
                g, h = obj_f(score, label, weights)
                # ONE [K, M, N] one-hot drives all four per-leaf
                # reductions/lookups as exact matmuls (each output sums
                # exactly one nonzero product per routed row)
                oh = (lf[:, None, :] == iota).astype(jnp.float32)
                gs = jnp.einsum("kmn,kn->km", oh, g, precision=P)
                hs = jnp.einsum("kmn,kn->km", oh, h, precision=P)
                cnt = jnp.einsum("kmn,n->km", oh, valid, precision=P)
                out = jnp.clip(leaf_output(gs, hs, l1, l2) * shr[:, None],
                               -100.0, 100.0)
                # hs > 0 guards the 0/0 Newton step a leaf of only
                # zero-WEIGHT rows would take (cnt counts valid rows
                # regardless of weight) — training's
                # min_sum_hessian_in_leaf invariant, kept minimal here
                upd = (cnt >= minr) & (hs > 0.0) & okk[:, None]
                new = decay * old + (1.0 - decay) * out
                new = jnp.where(upd, new, old)
                score = score + jnp.einsum("kmn,km->kn", oh, new,
                                           precision=P)
                return score, (new, upd)

            _, (new_lv, upd) = jax.lax.scan(body, score0,
                                            (leaf, old_lv, shrink, ok))
            return new_lv, upd
        return run

    # -- per-window refresh ---------------------------------------------

    def refit(self, leaf_idx: Optional[np.ndarray] = None) -> dict:
        """Refit every tree's leaf values on the dataset's CURRENT rows
        (mutates the model in place); returns a stats dict.

        leaf_idx: optional precomputed [num_data, num_trees] leaf
        indices (the C API's LGBM_BoosterRefit contract); the binned
        router runs when omitted.
        """
        from ..learner.common import sentinel_bins_t
        from ..ops.predict import predict_ensemble_leaf_binned
        gbdt, ds = self.gbdt, self.dataset
        gbdt._flush_pending()
        if len(gbdt.models) != self.T:
            raise LightGBMError("model structure changed since this "
                                "LeafRefitter was built; rebuild it")
        n, cap = ds.num_data, ds.row_capacity
        md = ds.metadata
        if n < 1:
            raise LightGBMError("refit needs at least one labeled row")
        if md.label.size != n:
            raise LightGBMError("refit data carries no labels")
        if leaf_idx is None:
            self._ensure_router()
            bins_t = jax.device_put(sentinel_bins_t(ds))
            leaf = predict_ensemble_leaf_binned(
                self._stack, bins_t, self._feat_tbl, meta=self._meta)
        else:
            li = np.asarray(leaf_idx, np.int32)
            if li.shape != (n, self.T):
                raise ValueError(
                    f"leaf_idx must be [{n}, {self.T}], got {li.shape}")
            li = np.ascontiguousarray(li.T)
            if cap > n:
                li = np.pad(li, ((0, 0), (0, cap - n)))
            leaf = jax.device_put(li)
        leaf = jnp.reshape(leaf, (self.iters, self.K, cap))

        lab = np.zeros(cap, np.int32 if self._label_int else np.float32)
        lab[:n] = (md.label.astype(np.int32) if self._label_int
                   else md.label.astype(np.float32))
        w = np.zeros(cap, np.float32)
        w[:n] = 1.0 if md.weights is None else md.weights.astype(np.float32)
        valid = np.zeros(cap, np.float32)
        valid[:n] = 1.0
        old = np.zeros((self.T, self.M), np.float32)
        for i, t in enumerate(gbdt.models):
            m = min(t.max_leaves, self.M)
            old[i, :m] = t.leaf_value[:m].astype(np.float32)
        shrink = np.asarray([t.shrinkage for t in gbdt.models], np.float32)
        sc0 = np.zeros((self.K, cap), np.float32)
        if md.init_score is not None:
            init = np.asarray(md.init_score, np.float64).reshape(-1)
            if init.size == n * self.K:
                sc0[:, :n] = init.reshape(self.K, n).astype(np.float32)
            elif init.size == n:
                sc0[:, :n] = init[None, :].astype(np.float32)
            else:
                raise LightGBMError("init score size mismatch")
        dev = jax.device_put((
            old.reshape(self.iters, self.K, self.M),
            shrink.reshape(self.iters, self.K),
            (~self._frozen).reshape(self.iters, self.K),
            lab, w, valid, sc0))
        new_lv, upd = jax.device_get(self._fn(leaf, *dev))
        flat = np.asarray(new_lv).reshape(self.T, self.M)
        updm = np.asarray(upd).reshape(self.T, self.M)
        changed = 0
        for i, t in enumerate(gbdt.models):
            if self._frozen[i] or self.decay == 1.0:
                # decay 1.0 is a documented freeze — and an UNCHANGED
                # leaf must keep its exact f64 value, not a round-trip
                # through the kernel's f32 (same for starved leaves
                # below, hence the update mask)
                continue
            m = t.num_leaves
            t.set_leaf_values(np.where(updm[i, :m],
                                       flat[i, :m].astype(np.float64),
                                       t.leaf_value[:m]))
            changed += 1
        gbdt._predict_stack_cache.clear()
        self.refits += 1
        return {"rows": int(n), "capacity": int(cap),
                "trees": int(self.T), "trees_refit": int(changed),
                "decay_rate": float(self.decay),
                "min_rows": int(self.min_rows)}


def refit_gbdt(gbdt, dataset, *, decay_rate: Optional[float] = None,
               min_rows: Optional[int] = None,
               leaf_idx: Optional[np.ndarray] = None) -> dict:
    """One-shot refit of `gbdt`'s leaf values on `dataset` (in place).
    Callers that refresh repeatedly should hold a LeafRefitter instead
    so the compiled programs are reused across windows."""
    return LeafRefitter(gbdt, dataset, decay_rate=decay_rate,
                        min_rows=min_rows).refit(leaf_idx=leaf_idx)
