"""Labeled-traffic ingestion: JSON-lines reader for logged /predict
traffic joined with labels.

Line format (one example per line):

    {"features": [f0, f1, ...], "label": y}
    {"features": [f0, f1, ...], "label": y, "weight": w}
    {"features": [...], "label": y, "model": "de"}   # catalog tenant
    [y, f0, f1, ...]                      # plain-array shorthand

which is exactly the serving request body's row shape
(serving/server.py `_parse_predict_body`) plus the joined label — a log
pipeline can append the label to each served row and feed the file
straight back into the trainer.

`TrafficLog` tails a GROWING file: it remembers its byte offset and
only consumes complete lines, so a writer appending mid-poll never
feeds the reader a torn record (the partial tail is re-read on the next
poll once its newline lands).

`TrafficDemux` is the multi-tenant reader (ROADMAP item 2 closed):
ONE tailer reads and JSON-parses the shared file once, and per-tenant
views replay the parsed records through their own tenant filter and
width check — poll cost scales with log bytes, not tenants × log
bytes, while each view keeps the exact `TrafficLog` surface (offset,
counters, seek, read_new) so `OnlineTrainer` and its crash-safe resume
work unchanged on top.
"""
from __future__ import annotations

import json
import os
import threading
from collections import deque
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..diagnostics import locksan


def append_traffic(path: str, X: np.ndarray, y: np.ndarray,
                   weight: Optional[np.ndarray] = None,
                   trace_ids=None, model_id: Optional[str] = None) -> int:
    """Append labeled rows to a traffic log (the writer half — what a
    serving-side label joiner produces); returns rows written.

    ``trace_ids`` (one per row, or one string for all rows; None
    entries allowed) stamps each record with the serving-side trace id
    of the /predict request that scored it — the hop that lets the
    online daemon's publish sidecar name the originating requests
    (docs/Observability.md propagation diagram).  ``model_id`` keys
    each record with the catalog tenant that served it, so N per-tenant
    daemons can share ONE traffic tail (each reads only its own rows —
    TrafficLog ``model_filter``); None keeps the unkeyed single-tenant
    record shape."""
    from ..diagnostics import faults
    X = np.asarray(X, np.float64)
    if X.ndim == 1:
        X = X.reshape(1, -1)
    y = np.asarray(y, np.float64).reshape(-1)
    if len(y) != len(X):
        raise ValueError("label length mismatch")
    if isinstance(trace_ids, str):
        trace_ids = [trace_ids] * len(X)
    if trace_ids is not None and len(trace_ids) != len(X):
        raise ValueError("trace_ids length mismatch")
    with open(path, "a") as f:
        for i in range(len(X)):
            rec = {"features": [float(v) for v in X[i]],
                   "label": float(y[i])}
            if model_id is not None:
                rec["model"] = str(model_id)
            if weight is not None:
                rec["weight"] = float(np.asarray(weight).reshape(-1)[i])
            if trace_ids is not None and trace_ids[i]:
                rec["trace_id"] = str(trace_ids[i])
            line = json.dumps(rec) + "\n"
            # chaos seam: a writer dying mid-append leaves a torn tail —
            # exactly what the reader's complete-lines-only contract
            # must absorb (tests/test_faults.py)
            if faults.fire("traffic.append"):
                f.write(line[: max(1, len(line) // 2)])
                f.flush()
                raise faults.InjectedFault("traffic.append", 0)
            f.write(line)
    return len(X)


class TrafficLog:
    """Incremental reader over a labeled-traffic JSONL file.

    `expected_features` pins the row width (the model's feature count);
    without it the width locks to the first well-formed line EVER read.
    Either way the reference persists across polls, so one short-but-
    parseable line can only lose itself — never become the yardstick
    that rejects every valid row behind it.

    `model_filter` keys the reader to ONE catalog tenant of a shared
    multi-tenant log: rows whose ``model`` field names another tenant
    are skipped (counted in ``filtered_rows`` — they are another
    daemon's data, not loss); rows with NO model field match only when
    `match_unkeyed` is true (the default tenant's daemon sets it, so
    pre-catalog writers keep feeding it).  No filter = read everything,
    the single-tenant behavior.
    """

    def __init__(self, path: str, expected_features: Optional[int] = None,
                 max_poll_bytes: int = 64 << 20,
                 model_filter: Optional[str] = None,
                 match_unkeyed: Optional[bool] = None):
        self.path = path
        self.offset = 0           # byte offset of the first unread line
        self.rows_read = 0
        self.bad_lines = 0
        self.overcap_skips = 0    # single lines larger than max_poll_bytes
        self.filtered_rows = 0    # other tenants' rows (not data loss)
        self._model_filter = (str(model_filter)
                              if model_filter is not None else None)
        # unfiltered readers take every row incl. unkeyed ones; a
        # keyed reader skips unkeyed rows unless told otherwise
        self._match_unkeyed = (model_filter is None
                               if match_unkeyed is None
                               else bool(match_unkeyed))
        self._width = (int(expected_features)
                       if expected_features else None)
        # per-poll read cap: a daemon (re)started against a multi-GB
        # backlog must drain it in bounded slices, not one giant blob
        self._max_poll = int(max_poll_bytes)
        # trace ids of the rows the LAST read_new() returned (aligned
        # with its X; None where the record carried none) — the
        # serve→train trace-propagation hop the online trainer folds
        # into its window provenance
        self.last_trace_ids: list = []

    def counters(self) -> dict:
        """Silent-data-loss evidence for /stats (docs/Robustness.md):
        rows consumed, malformed lines skipped, over-cap lines skipped,
        other-tenant rows filtered, and the current byte offset."""
        return {"offset": int(self.offset), "rows_read": int(self.rows_read),
                "bad_lines": int(self.bad_lines),
                "overcap_skips": int(self.overcap_skips),
                "filtered_rows": int(self.filtered_rows)}

    def seek(self, offset: int, counters: Optional[dict] = None) -> None:
        """Restore a persisted read position (daemon restart): the next
        read_new() continues from `offset` instead of byte 0."""
        self.offset = max(0, int(offset))
        if counters:
            self.rows_read = int(counters.get("rows_read", self.rows_read))
            self.bad_lines = int(counters.get("bad_lines", self.bad_lines))
            self.overcap_skips = int(counters.get("overcap_skips",
                                                  self.overcap_skips))
            self.filtered_rows = int(counters.get("filtered_rows",
                                                  self.filtered_rows))

    def read_new(self) -> Optional[Tuple[np.ndarray, np.ndarray,
                                         Optional[np.ndarray]]]:
        """Consume every COMPLETE line past the last offset.

        Returns (X, y, weights-or-None), or None when nothing new is
        readable.  A file that shrank (rotation/truncation) restarts
        from the top.  Malformed lines are counted and skipped — one
        bad record must not wedge the ingestion loop.
        """
        try:
            size = os.path.getsize(self.path)
        except OSError:
            return None
        if size < self.offset:      # rotated/truncated: start over
            self.offset = 0
        if size == self.offset:
            return None
        capped = size - self.offset > self._max_poll
        with open(self.path, "rb") as f:
            f.seek(self.offset)
            blob = f.read(min(size - self.offset, self._max_poll))
        last_nl = blob.rfind(b"\n")
        if last_nl < 0:
            if capped:              # a single over-cap line: skip it
                # (its remainder parses as one more bad line later)
                self.offset += len(blob)
                self.bad_lines += 1
                self.overcap_skips += 1
            return None             # else: only a torn tail so far
        consumed = blob[: last_nl + 1]
        self.offset += len(consumed)
        feats, labels, weights, traces = [], [], [], []
        any_weight = False
        for line in consumed.decode("utf-8", errors="replace").splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                item = json.loads(line)
                if isinstance(item, dict):
                    rec_model = item.get("model")
                    row = [float(v) for v in item["features"]]
                    lab = float(item["label"])
                    w = item.get("weight")
                    tr = item.get("trace_id")
                else:               # [label, f0, f1, ...] shorthand
                    rec_model = None
                    lab = float(item[0])
                    row = [float(v) for v in item[1:]]
                    w = None
                    tr = None
            except (ValueError, TypeError, KeyError, IndexError):
                self.bad_lines += 1
                continue
            # tenant keying: another tenant's (well-formed) row is
            # filtered, not "bad" — it is some other daemon's data
            if rec_model is None:
                if not self._match_unkeyed:
                    self.filtered_rows += 1
                    continue
            elif (self._model_filter is not None
                    and str(rec_model) != self._model_filter):
                self.filtered_rows += 1
                continue
            if self._width is None:
                self._width = len(row)
            if len(row) != self._width:
                self.bad_lines += 1
                continue
            feats.append(row)
            labels.append(lab)
            weights.append(1.0 if w is None else float(w))
            traces.append(str(tr) if tr is not None else None)
            any_weight = any_weight or w is not None
        if not feats:
            return None
        self.last_trace_ids = traces
        self.rows_read += len(feats)
        X = np.asarray(feats, np.float64)
        y = np.asarray(labels, np.float64)
        w = np.asarray(weights, np.float32) if any_weight else None
        return X, y, w


class _DemuxRecord:
    """One parsed line of the shared log, held in the demux window.

    ``start``/``end`` are the line's byte span in the file — the replay
    cursor every view compares its own offset against.  ``kind`` is
    "row" (parsed fields attached), "bad" (unparseable — charged to
    every view, exactly as N independent readers would each have
    charged it), or "overcap" (a single line larger than the poll cap).
    """

    __slots__ = ("start", "end", "kind", "model", "row", "label",
                 "weight", "trace")

    def __init__(self, start: int, end: int, kind: str,
                 model: Optional[str] = None, row: Optional[list] = None,
                 label: float = 0.0, weight: Optional[float] = None,
                 trace: Optional[str] = None):
        self.start = start
        self.end = end
        self.kind = kind
        self.model = model
        self.row = row
        self.label = label
        self.weight = weight
        self.trace = trace


class TrafficDemux:
    """ONE tailer over a shared multi-tenant traffic log, fanned out to
    per-tenant views.

    The pre-demux fleet ran N independent `TrafficLog` readers over the
    same file: every poll cycle read and JSON-parsed the full append
    window N times, once per tenant.  The demux reads and parses each
    byte ONCE into a window of `_DemuxRecord`s; each `view()` replays
    the records past its own byte offset through its own tenant filter
    and width check.  Poll cost scales with log bytes, not
    tenants x log bytes.

    Contract: every view must be polled regularly (the fleet polls all
    daemons each cycle).  The window is pruned to the slowest view's
    offset, so a view that stops reading pins records in memory.
    Views may resume at different persisted offsets — the parse cursor
    starts at the MINIMUM view offset, and a view seeking backward
    below the window rewinds the shared cursor (other views skip the
    re-parsed span via their own offsets).  All entry points take one
    lock, so views are safe to poll from different threads too.
    """

    def __init__(self, path: str, max_poll_bytes: int = 64 << 20):
        self.path = path
        self._max_poll = int(max_poll_bytes)
        self._lock = locksan.lock("online.stream")
        self._views: List["TrafficDemuxView"] = []
        self._records: deque = deque()
        self._pos: Optional[int] = None   # parse cursor; lazy until the
        #                                   first poll so views can seek
        #                                   persisted offsets first

    def view(self, model_filter: Optional[str] = None,
             match_unkeyed: Optional[bool] = None,
             expected_features: Optional[int] = None) -> "TrafficDemuxView":
        """Create a per-tenant view (same keying semantics as
        `TrafficLog`: model_filter / match_unkeyed / width pin)."""
        v = TrafficDemuxView(self, model_filter=model_filter,
                             match_unkeyed=match_unkeyed,
                             expected_features=expected_features)
        with self._lock:
            self._views.append(v)
        return v

    # -- internal: called by views under self._lock ------------------

    def _advance(self) -> Optional[int]:
        """Parse newly appended bytes once; returns the current file
        size, or None when the file is not statable."""
        try:
            size = os.path.getsize(self.path)
        except OSError:
            return None
        # per-view rotation semantics, identical to TrafficLog: only a
        # view whose offset points past the shrunken file restarts
        for v in self._views:
            if size < v.offset:
                v.offset = 0
        lo = min((v.offset for v in self._views), default=0)
        window_start = (self._records[0].start if self._records
                        else self._pos)
        if (self._pos is None or lo < (window_start or 0)
                or size < self._pos):
            # first poll, a backward seek below the window, or rotation:
            # restart the parse at the slowest view
            self._records.clear()
            self._pos = lo
        if size == self._pos:
            return size
        capped = size - self._pos > self._max_poll
        with open(self.path, "rb") as f:
            f.seek(self._pos)
            blob = f.read(min(size - self._pos, self._max_poll))
        last_nl = blob.rfind(b"\n")
        if last_nl < 0:
            if capped:              # one over-cap line: record the skip
                self._records.append(_DemuxRecord(
                    self._pos, self._pos + len(blob), "overcap"))
                self._pos += len(blob)
            return size             # else: only a torn tail so far
        consumed = blob[: last_nl + 1]
        off = self._pos
        for raw in consumed[:-1].split(b"\n"):
            start, end = off, off + len(raw) + 1
            off = end
            line = raw.decode("utf-8", errors="replace").strip()
            if not line:
                continue
            try:
                item = json.loads(line)
                if isinstance(item, dict):
                    model = (str(item["model"])
                             if item.get("model") is not None else None)
                    row = [float(v) for v in item["features"]]
                    lab = float(item["label"])
                    w = (float(item["weight"])
                         if item.get("weight") is not None else None)
                    tr = item.get("trace_id")
                else:               # [label, f0, f1, ...] shorthand
                    model = None
                    lab = float(item[0])
                    row = [float(v) for v in item[1:]]
                    w = None
                    tr = None
            except (ValueError, TypeError, KeyError, IndexError):
                self._records.append(_DemuxRecord(start, end, "bad"))
                continue
            self._records.append(_DemuxRecord(
                start, end, "row", model=model, row=row, label=lab,
                weight=w, trace=str(tr) if tr is not None else None))
        self._pos = off
        return size

    def _prune(self) -> None:
        """Drop records every view has replayed past."""
        lo = min((v.offset for v in self._views), default=0)
        while self._records and self._records[0].end <= lo:
            self._records.popleft()


class TrafficDemuxView:
    """One tenant's replay cursor over a `TrafficDemux` window.

    Exposes the full `TrafficLog` surface — path / offset / counters /
    seek / read_new / last_trace_ids — so `OnlineTrainer` (including
    its crash-safe offset resume) runs on a view unchanged.  Counter
    semantics match an independent `TrafficLog` with the same filter:
    bad and over-cap lines charge EVERY view (each of the old N readers
    parsed and skipped them itself), other-tenant rows land in this
    view's ``filtered_rows``, and the width pin is per-view.
    """

    def __init__(self, demux: TrafficDemux,
                 model_filter: Optional[str] = None,
                 match_unkeyed: Optional[bool] = None,
                 expected_features: Optional[int] = None):
        self._demux = demux
        self.offset = 0
        self.rows_read = 0
        self.bad_lines = 0
        self.overcap_skips = 0
        self.filtered_rows = 0
        self._model_filter = (str(model_filter)
                              if model_filter is not None else None)
        self._match_unkeyed = (model_filter is None
                               if match_unkeyed is None
                               else bool(match_unkeyed))
        self._width = (int(expected_features)
                       if expected_features else None)
        self.last_trace_ids: list = []

    @property
    def path(self) -> str:
        return self._demux.path

    counters = TrafficLog.counters
    seek = TrafficLog.seek

    def read_new(self) -> Optional[Tuple[np.ndarray, np.ndarray,
                                         Optional[np.ndarray]]]:
        """Advance the shared tailer, then replay every window record
        past this view's offset through its tenant filter.  Same return
        contract as `TrafficLog.read_new`."""
        with self._demux._lock:
            if self._demux._advance() is None:
                return None
            feats, labels, weights, traces = [], [], [], []
            any_weight = False
            for rec in self._demux._records:
                if rec.start < self.offset:
                    continue
                if rec.kind == "overcap":
                    self.bad_lines += 1
                    self.overcap_skips += 1
                    continue
                if rec.kind == "bad":
                    self.bad_lines += 1
                    continue
                if rec.model is None:
                    if not self._match_unkeyed:
                        self.filtered_rows += 1
                        continue
                elif (self._model_filter is not None
                        and rec.model != self._model_filter):
                    self.filtered_rows += 1
                    continue
                if self._width is None:
                    self._width = len(rec.row)
                if len(rec.row) != self._width:
                    self.bad_lines += 1
                    continue
                feats.append(rec.row)
                labels.append(rec.label)
                weights.append(1.0 if rec.weight is None else rec.weight)
                traces.append(rec.trace)
                any_weight = any_weight or rec.weight is not None
            self.offset = int(self._demux._pos or 0)
            self._demux._prune()
        if not feats:
            return None
        self.last_trace_ids = traces
        self.rows_read += len(feats)
        X = np.asarray(feats, np.float64)
        y = np.asarray(labels, np.float64)
        w = np.asarray(weights, np.float32) if any_weight else None
        return X, y, w
