"""Labeled-traffic ingestion: JSON-lines reader for logged /predict
traffic joined with labels.

Line format (one example per line):

    {"features": [f0, f1, ...], "label": y}
    {"features": [f0, f1, ...], "label": y, "weight": w}
    {"features": [...], "label": y, "model": "de"}   # catalog tenant
    [y, f0, f1, ...]                      # plain-array shorthand

which is exactly the serving request body's row shape
(serving/server.py `_parse_predict_body`) plus the joined label — a log
pipeline can append the label to each served row and feed the file
straight back into the trainer.

`TrafficLog` tails a GROWING file: it remembers its byte offset and
only consumes complete lines, so a writer appending mid-poll never
feeds the reader a torn record (the partial tail is re-read on the next
poll once its newline lands).
"""
from __future__ import annotations

import json
import os
from typing import Optional, Tuple

import numpy as np


def append_traffic(path: str, X: np.ndarray, y: np.ndarray,
                   weight: Optional[np.ndarray] = None,
                   trace_ids=None, model_id: Optional[str] = None) -> int:
    """Append labeled rows to a traffic log (the writer half — what a
    serving-side label joiner produces); returns rows written.

    ``trace_ids`` (one per row, or one string for all rows; None
    entries allowed) stamps each record with the serving-side trace id
    of the /predict request that scored it — the hop that lets the
    online daemon's publish sidecar name the originating requests
    (docs/Observability.md propagation diagram).  ``model_id`` keys
    each record with the catalog tenant that served it, so N per-tenant
    daemons can share ONE traffic tail (each reads only its own rows —
    TrafficLog ``model_filter``); None keeps the unkeyed single-tenant
    record shape."""
    from ..diagnostics import faults
    X = np.asarray(X, np.float64)
    if X.ndim == 1:
        X = X.reshape(1, -1)
    y = np.asarray(y, np.float64).reshape(-1)
    if len(y) != len(X):
        raise ValueError("label length mismatch")
    if isinstance(trace_ids, str):
        trace_ids = [trace_ids] * len(X)
    if trace_ids is not None and len(trace_ids) != len(X):
        raise ValueError("trace_ids length mismatch")
    with open(path, "a") as f:
        for i in range(len(X)):
            rec = {"features": [float(v) for v in X[i]],
                   "label": float(y[i])}
            if model_id is not None:
                rec["model"] = str(model_id)
            if weight is not None:
                rec["weight"] = float(np.asarray(weight).reshape(-1)[i])
            if trace_ids is not None and trace_ids[i]:
                rec["trace_id"] = str(trace_ids[i])
            line = json.dumps(rec) + "\n"
            # chaos seam: a writer dying mid-append leaves a torn tail —
            # exactly what the reader's complete-lines-only contract
            # must absorb (tests/test_faults.py)
            if faults.fire("traffic.append"):
                f.write(line[: max(1, len(line) // 2)])
                f.flush()
                raise faults.InjectedFault("traffic.append", 0)
            f.write(line)
    return len(X)


class TrafficLog:
    """Incremental reader over a labeled-traffic JSONL file.

    `expected_features` pins the row width (the model's feature count);
    without it the width locks to the first well-formed line EVER read.
    Either way the reference persists across polls, so one short-but-
    parseable line can only lose itself — never become the yardstick
    that rejects every valid row behind it.

    `model_filter` keys the reader to ONE catalog tenant of a shared
    multi-tenant log: rows whose ``model`` field names another tenant
    are skipped (counted in ``filtered_rows`` — they are another
    daemon's data, not loss); rows with NO model field match only when
    `match_unkeyed` is true (the default tenant's daemon sets it, so
    pre-catalog writers keep feeding it).  No filter = read everything,
    the single-tenant behavior.
    """

    def __init__(self, path: str, expected_features: Optional[int] = None,
                 max_poll_bytes: int = 64 << 20,
                 model_filter: Optional[str] = None,
                 match_unkeyed: Optional[bool] = None):
        self.path = path
        self.offset = 0           # byte offset of the first unread line
        self.rows_read = 0
        self.bad_lines = 0
        self.overcap_skips = 0    # single lines larger than max_poll_bytes
        self.filtered_rows = 0    # other tenants' rows (not data loss)
        self._model_filter = (str(model_filter)
                              if model_filter is not None else None)
        # unfiltered readers take every row incl. unkeyed ones; a
        # keyed reader skips unkeyed rows unless told otherwise
        self._match_unkeyed = (model_filter is None
                               if match_unkeyed is None
                               else bool(match_unkeyed))
        self._width = (int(expected_features)
                       if expected_features else None)
        # per-poll read cap: a daemon (re)started against a multi-GB
        # backlog must drain it in bounded slices, not one giant blob
        self._max_poll = int(max_poll_bytes)
        # trace ids of the rows the LAST read_new() returned (aligned
        # with its X; None where the record carried none) — the
        # serve→train trace-propagation hop the online trainer folds
        # into its window provenance
        self.last_trace_ids: list = []

    def counters(self) -> dict:
        """Silent-data-loss evidence for /stats (docs/Robustness.md):
        rows consumed, malformed lines skipped, over-cap lines skipped,
        other-tenant rows filtered, and the current byte offset."""
        return {"offset": int(self.offset), "rows_read": int(self.rows_read),
                "bad_lines": int(self.bad_lines),
                "overcap_skips": int(self.overcap_skips),
                "filtered_rows": int(self.filtered_rows)}

    def seek(self, offset: int, counters: Optional[dict] = None) -> None:
        """Restore a persisted read position (daemon restart): the next
        read_new() continues from `offset` instead of byte 0."""
        self.offset = max(0, int(offset))
        if counters:
            self.rows_read = int(counters.get("rows_read", self.rows_read))
            self.bad_lines = int(counters.get("bad_lines", self.bad_lines))
            self.overcap_skips = int(counters.get("overcap_skips",
                                                  self.overcap_skips))
            self.filtered_rows = int(counters.get("filtered_rows",
                                                  self.filtered_rows))

    def read_new(self) -> Optional[Tuple[np.ndarray, np.ndarray,
                                         Optional[np.ndarray]]]:
        """Consume every COMPLETE line past the last offset.

        Returns (X, y, weights-or-None), or None when nothing new is
        readable.  A file that shrank (rotation/truncation) restarts
        from the top.  Malformed lines are counted and skipped — one
        bad record must not wedge the ingestion loop.
        """
        try:
            size = os.path.getsize(self.path)
        except OSError:
            return None
        if size < self.offset:      # rotated/truncated: start over
            self.offset = 0
        if size == self.offset:
            return None
        capped = size - self.offset > self._max_poll
        with open(self.path, "rb") as f:
            f.seek(self.offset)
            blob = f.read(min(size - self.offset, self._max_poll))
        last_nl = blob.rfind(b"\n")
        if last_nl < 0:
            if capped:              # a single over-cap line: skip it
                # (its remainder parses as one more bad line later)
                self.offset += len(blob)
                self.bad_lines += 1
                self.overcap_skips += 1
            return None             # else: only a torn tail so far
        consumed = blob[: last_nl + 1]
        self.offset += len(consumed)
        feats, labels, weights, traces = [], [], [], []
        any_weight = False
        for line in consumed.decode("utf-8", errors="replace").splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                item = json.loads(line)
                if isinstance(item, dict):
                    rec_model = item.get("model")
                    row = [float(v) for v in item["features"]]
                    lab = float(item["label"])
                    w = item.get("weight")
                    tr = item.get("trace_id")
                else:               # [label, f0, f1, ...] shorthand
                    rec_model = None
                    lab = float(item[0])
                    row = [float(v) for v in item[1:]]
                    w = None
                    tr = None
            except (ValueError, TypeError, KeyError, IndexError):
                self.bad_lines += 1
                continue
            # tenant keying: another tenant's (well-formed) row is
            # filtered, not "bad" — it is some other daemon's data
            if rec_model is None:
                if not self._match_unkeyed:
                    self.filtered_rows += 1
                    continue
            elif (self._model_filter is not None
                    and str(rec_model) != self._model_filter):
                self.filtered_rows += 1
                continue
            if self._width is None:
                self._width = len(row)
            if len(row) != self._width:
                self.bad_lines += 1
                continue
            feats.append(row)
            labels.append(lab)
            weights.append(1.0 if w is None else float(w))
            traces.append(str(tr) if tr is not None else None)
            any_weight = any_weight or w is not None
        if not feats:
            return None
        self.last_trace_ids = traces
        self.rows_read += len(feats)
        X = np.asarray(feats, np.float64)
        y = np.asarray(labels, np.float64)
        w = np.asarray(weights, np.float32) if any_weight else None
        return X, y, w
