"""Online learning: streaming ingestion, leaf refit, continuous publish.

Closes the train→serve loop (ROADMAP item 5): models whose STRUCTURE
was trained offline get their leaf VALUES refreshed continuously from
labeled serving traffic, and each refreshed generation is published
atomically to the path the serving ModelRegistry hot-swaps from — the
production drift story with zero recompiles on the serving side.

- `stream` — JSONL labeled-traffic reader + the Dataset append path's
  front end (frozen bin mappers, capacity-tiered store growth);
- `refit` — the leaf-value refit kernel (one binned ensemble traversal
  to route rows, one jitted scan to recompute every tree's leaves:
  reference GBDT::RefitTree semantics, `refit_decay_rate` blending,
  `refit_min_rows` starvation guard);
- `trainer` — the `task=online` daemon (watch traffic, refit or
  continue-boost on trigger, publish generations + metadata sidecar).
"""
from .refit import LeafRefitter, refit_gbdt
from .stream import TrafficLog, append_traffic
from .trainer import OnlineTrainer

__all__ = ["LeafRefitter", "refit_gbdt", "TrafficLog", "append_traffic",
           "OnlineTrainer"]
