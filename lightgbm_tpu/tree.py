"""Flat-array decision tree model.

Mirrors the reference Tree (/root/reference/include/LightGBM/tree.h:18-197,
src/io/tree.cpp): same node-index convention (internal nodes 0..n-2, leaves
referenced as ~leaf_index in child arrays), same Split() bookkeeping
(tree.cpp:52-97), same text serialization keys (tree.cpp:295-330) so model
files interoperate with LightGBM, same ±100 output clamp on Shrinkage
(tree.h:104-112).

The host owns the authoritative numpy arrays (they are mutated during
growth); `as_device_arrays` exports padded jnp arrays for vectorized binned
traversal on device (the TPU analog of AddPredictionToScore's BinIterator
walk, tree.cpp:99-192).
"""
from __future__ import annotations

import json
from typing import Dict, List, Optional

import numpy as np

K_MAX_TREE_OUTPUT = 100.0  # reference tree.h kMaxTreeOutput

NUMERICAL_DECISION = 0
CATEGORICAL_DECISION = 1


def _arr_to_str(a, fmt="{:g}") -> str:
    return " ".join(fmt.format(x) for x in a)


class Tree:
    def __init__(self, max_leaves: int):
        self.max_leaves = max_leaves
        m = max_leaves
        self.num_leaves = 1
        self.left_child = np.zeros(m - 1, np.int32)
        self.right_child = np.zeros(m - 1, np.int32)
        self.split_feature_inner = np.zeros(m - 1, np.int32)
        self.split_feature = np.zeros(m - 1, np.int32)
        self.threshold_in_bin = np.zeros(m - 1, np.int64)
        self.threshold = np.zeros(m - 1, np.float64)
        self.decision_type = np.zeros(m - 1, np.int8)
        self.split_gain = np.zeros(m - 1, np.float64)
        self.leaf_parent = np.full(m, -1, np.int32)
        self.leaf_value = np.zeros(m, np.float64)
        self.leaf_count = np.zeros(m, np.int64)
        self.internal_value = np.zeros(m - 1, np.float64)
        self.internal_count = np.zeros(m - 1, np.int64)
        self.leaf_depth = np.zeros(m, np.int32)
        self.shrinkage = 1.0
        self.has_categorical = False
        self._device_cache = None

    # -- growth (reference tree.cpp:52-97) ---------------------------------

    def split(self, leaf: int, inner_feature: int, bin_type: int,
              threshold_bin: int, real_feature: int, threshold_double: float,
              left_value: float, right_value: float, left_cnt: int,
              right_cnt: int, gain: float) -> int:
        new_node = self.num_leaves - 1
        parent = self.leaf_parent[leaf]
        if parent >= 0:
            if self.left_child[parent] == ~leaf:
                self.left_child[parent] = new_node
            else:
                self.right_child[parent] = new_node
        self.split_feature_inner[new_node] = inner_feature
        self.split_feature[new_node] = real_feature
        if bin_type == NUMERICAL_DECISION:
            self.decision_type[new_node] = 0
        else:
            self.decision_type[new_node] = 1
            self.has_categorical = True
        self.threshold_in_bin[new_node] = threshold_bin
        self.threshold[new_node] = threshold_double
        self.split_gain[new_node] = np.finfo(np.float64).max if np.isinf(gain) else gain
        self.left_child[new_node] = ~leaf
        self.right_child[new_node] = ~self.num_leaves
        self.leaf_parent[leaf] = new_node
        self.leaf_parent[self.num_leaves] = new_node
        self.internal_value[new_node] = self.leaf_value[leaf]
        self.internal_count[new_node] = left_cnt + right_cnt
        self.leaf_value[leaf] = 0.0 if np.isnan(left_value) else left_value
        self.leaf_count[leaf] = left_cnt
        self.leaf_value[self.num_leaves] = 0.0 if np.isnan(right_value) else right_value
        self.leaf_count[self.num_leaves] = right_cnt
        self.leaf_depth[self.num_leaves] = self.leaf_depth[leaf] + 1
        self.leaf_depth[leaf] += 1
        self.num_leaves += 1
        self._device_cache = None
        return self.num_leaves - 1

    def apply_shrinkage(self, rate: float) -> None:
        lv = self.leaf_value[: self.num_leaves] * rate
        np.clip(lv, -K_MAX_TREE_OUTPUT, K_MAX_TREE_OUTPUT, out=lv)
        self.leaf_value[: self.num_leaves] = lv
        self.shrinkage *= rate
        self._device_cache = None

    def set_leaf_values(self, values: np.ndarray) -> None:
        self.leaf_value[: self.num_leaves] = values[: self.num_leaves]
        self._device_cache = None

    @property
    def max_depth_grown(self) -> int:
        return int(self.leaf_depth[: self.num_leaves].max()) if self.num_leaves > 1 else 0

    # -- prediction on raw feature values (reference tree.h:217-241) -------

    def predict_raw(self, X: np.ndarray) -> np.ndarray:
        """Vectorized node walk on raw feature values ([N, num_raw_features])."""
        n = X.shape[0]
        if self.num_leaves <= 1:
            return np.full(n, self.leaf_value[0])
        leaf = self.predict_leaf_index(X)
        return self.leaf_value[leaf]

    def predict_leaf_index(self, X: np.ndarray) -> np.ndarray:
        n = X.shape[0]
        if self.num_leaves <= 1:
            return np.zeros(n, np.int32)
        node = np.zeros(n, np.int32)
        active = node >= 0
        while np.any(active):
            f = self.split_feature[node[active]]
            v = X[active, f]
            thr = self.threshold[node[active]]
            dec = self.decision_type[node[active]]
            # non-finite values on a categorical split always go RIGHT
            # here, while training-time binning maps NaN to value 0
            # (binning.py value_to_bin), which can land in category 0's
            # bin — the reference has the same train/predict asymmetry
            # (its raw predict casts NaN with static_cast<int>, tree.h:
            # 217-241, never matching a category); we emulate it rather
            # than diverge from reference predictions on NaN rows
            finite = np.isfinite(v)
            vi = np.where(finite, v, -1.0).astype(np.int64)
            go_left = np.where(dec == 0, v <= thr,
                               finite & (vi == thr.astype(np.int64)))
            nxt = np.where(go_left, self.left_child[node[active]],
                           self.right_child[node[active]])
            node[active] = nxt
            active = node >= 0
        return (~node).astype(np.int32)

    # -- device export ------------------------------------------------------

    def as_device_arrays(self):
        """Padded arrays for on-device binned traversal.

        Child pointers: internal >= 0, leaves encoded as ~leaf (negative).
        """
        if self._device_cache is None:
            import jax
            # CAPACITY shapes, not grown size: slicing to num_leaves-1
            # keyed the downstream jit (predict_binned_leaf) on every
            # distinct tree size — one silent retrace per new shape in
            # the boosting loop.  Padding slots are unreachable from the
            # root walk, so their (zero) contents never matter.
            n = max(self.max_leaves - 1, 1)
            binned_dec = getattr(self, "binned_decision_type",
                                 self.decision_type)
            # ONE explicit pytree upload (jax.device_put): per-array
            # jnp.asarray was six implicit transfers per new tree inside
            # the boosting loop (sanitizer transfer-guard violations)
            host = dict(
                split_feature_inner=self.split_feature_inner[:n],
                threshold_in_bin=self.threshold_in_bin[:n].astype(np.int32),
                decision_type=binned_dec[:n].astype(np.int32),
                left_child=self.left_child[:n],
                right_child=self.right_child[:n],
                leaf_value=self.leaf_value[: max(self.max_leaves, 1)
                                           ].astype(np.float32),
            )
            # depth rounds up to a power of two: it is a static jit arg,
            # and the raw grown depth would retrace per new value; extra
            # walk levels are no-ops (rows parked at leaves stay parked)
            depth = max(self.max_depth_grown, 1)
            depth = 1 << (depth - 1).bit_length()
            self._device_cache = dict(jax.device_put(host), depth=depth)
        return self._device_cache

    # -- serialization (reference tree.cpp:295-330) -------------------------

    def to_string(self) -> str:
        n = self.num_leaves
        lines = [
            f"num_leaves={n}",
            "split_feature=" + _arr_to_str(self.split_feature[: n - 1], "{:d}"),
            "split_gain=" + _arr_to_str(self.split_gain[: n - 1]),
            "threshold=" + _arr_to_str(self.threshold[: n - 1], "{:.17g}"),
            "decision_type=" + _arr_to_str(self.decision_type[: n - 1], "{:d}"),
            "left_child=" + _arr_to_str(self.left_child[: n - 1], "{:d}"),
            "right_child=" + _arr_to_str(self.right_child[: n - 1], "{:d}"),
            "leaf_parent=" + _arr_to_str(self.leaf_parent[:n], "{:d}"),
            "leaf_value=" + _arr_to_str(self.leaf_value[:n], "{:.17g}"),
            "leaf_count=" + _arr_to_str(self.leaf_count[:n], "{:d}"),
            "internal_value=" + _arr_to_str(self.internal_value[: n - 1]),
            "internal_count=" + _arr_to_str(self.internal_count[: n - 1], "{:d}"),
            f"shrinkage={self.shrinkage:g}",
            f"has_categorical={1 if self.has_categorical else 0}",
            "",
        ]
        return "\n".join(lines) + "\n"

    @staticmethod
    def from_string(s: str) -> "Tree":
        kv: Dict[str, str] = {}
        for line in s.splitlines():
            if "=" in line:
                k, v = line.split("=", 1)
                if k.strip() and v.strip():
                    kv[k.strip()] = v.strip()
        if "num_leaves" not in kv:
            raise ValueError("Tree model string must contain num_leaves")
        n = int(kv["num_leaves"])
        t = Tree(max(n, 2))
        t.num_leaves = n
        if n <= 1:
            if "leaf_value" in kv:
                t.leaf_value[0] = float(kv["leaf_value"].split()[0])
            return t

        def ints(key):
            return np.array([int(x) for x in kv[key].split()])

        def floats(key):
            return np.array([float(x) for x in kv[key].split()])

        t.left_child[: n - 1] = ints("left_child")
        t.right_child[: n - 1] = ints("right_child")
        t.split_feature[: n - 1] = ints("split_feature")
        t.split_feature_inner[: n - 1] = t.split_feature[: n - 1]
        t.threshold[: n - 1] = floats("threshold")
        t.split_gain[: n - 1] = floats("split_gain")
        t.leaf_value[:n] = floats("leaf_value")
        if "decision_type" in kv:
            t.decision_type[: n - 1] = ints("decision_type").astype(np.int8)
            t.has_categorical = bool((t.decision_type[: n - 1] == 1).any())
        if "leaf_parent" in kv:
            t.leaf_parent[:n] = ints("leaf_parent")
        if "leaf_count" in kv:
            t.leaf_count[:n] = ints("leaf_count")
        if "internal_value" in kv:
            t.internal_value[: n - 1] = floats("internal_value")
        if "internal_count" in kv:
            t.internal_count[: n - 1] = ints("internal_count")
        if "shrinkage" in kv:
            t.shrinkage = float(kv["shrinkage"])
        # leaf_depth is not part of the model text — reconstruct it (the
        # binned traversal walks `max_depth_grown` levels)
        depth = np.zeros(n - 1, np.int32)
        stack = [(0, 0)]
        while stack:
            node, d = stack.pop()
            depth[node] = d
            for child in (t.left_child[node], t.right_child[node]):
                if child >= 0:
                    stack.append((int(child), d + 1))
                else:
                    t.leaf_depth[~child] = d + 1
        t.needs_rebin = True
        return t

    def rebin_to_dataset(self, dataset) -> None:
        """Reconstruct in-bin thresholds and inner feature indices for a
        tree loaded from model text (which stores only real feature ids and
        real-valued thresholds, tree.cpp:295+).  Needed before binned
        score-updater replay; saved thresholds are bin upper bounds, so
        value_to_bin recovers the original bin exactly.

        Only loaded trees rebin (in-session trees already carry in-bin data
        for the training mappers, which validation sets share); re-invoked
        with a DIFFERENT dataset, a loaded tree rebins again from the
        preserved real-valued thresholds.
        """
        if not getattr(self, "needs_rebin", False):
            return
        if getattr(self, "_rebin_dataset", None) is dataset:
            return
        # binned traversal may need a different decision op than the raw
        # one (trivial-feature sentinels below); raw predict keeps using
        # self.decision_type, the binned walk uses this override
        self.binned_decision_type = self.decision_type.copy()
        for node in range(self.num_leaves - 1):
            real = int(self.split_feature[node])
            inner = dataset.real_to_inner(real)
            mapper = dataset.mappers[real]
            if inner >= 0:
                self.split_feature_inner[node] = inner
                self.threshold_in_bin[node] = int(mapper.value_to_bin(
                    np.array([self.threshold[node]]))[0])
                self.binned_decision_type[node] = self.decision_type[node]
            else:
                # feature filtered as trivial in this dataset: every row
                # has the same value, so the comparison has one outcome —
                # encode as an always-left (huge bin) or always-right (-1)
                # NUMERICAL test on feature 0 (bins are never negative)
                c = mapper.bin_to_value(0)
                if self.decision_type[node] == CATEGORICAL_DECISION:
                    left = c == self.threshold[node]
                else:
                    left = c <= self.threshold[node]
                self.split_feature_inner[node] = 0
                self.threshold_in_bin[node] = (1 << 30) if left else -1
                self.binned_decision_type[node] = NUMERICAL_DECISION
        self._rebin_dataset = dataset
        self._device_cache = None

    def to_json(self) -> Dict:
        def node_json(index: int) -> Dict:
            if index >= 0:
                return {
                    "split_index": int(index),
                    "split_feature": int(self.split_feature[index]),
                    "split_gain": float(self.split_gain[index]),
                    "threshold": float(self.threshold[index]),
                    # reference names (tree.h GetDecisionTypeName):
                    # numerical "no_greater", categorical "is"
                    "decision_type": ("is" if self.decision_type[index] == 1
                                      else "no_greater"),
                    "internal_value": float(self.internal_value[index]),
                    "internal_count": int(self.internal_count[index]),
                    "left_child": node_json(int(self.left_child[index])),
                    "right_child": node_json(int(self.right_child[index])),
                }
            leaf = ~index
            return {
                "leaf_index": int(leaf),
                "leaf_parent": int(self.leaf_parent[leaf]),
                "leaf_value": float(self.leaf_value[leaf]),
                "leaf_count": int(self.leaf_count[leaf]),
            }

        return {
            "num_leaves": int(self.num_leaves),
            "shrinkage": float(self.shrinkage),
            "has_categorical": 1 if self.has_categorical else 0,
            "tree_structure": node_json(0) if self.num_leaves > 1 else {
                "leaf_index": 0, "leaf_value": float(self.leaf_value[0]),
                "leaf_parent": -1, "leaf_count": int(self.leaf_count[0])},
        }
