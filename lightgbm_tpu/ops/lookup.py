"""Small-table row lookups as one-hot matmuls.

XLA:TPU lowers `table[ids]` for a [N]-sized `ids` to a serialized gather
that runs at well under 1 GB/s — measured 65 ms for a 256-entry lookup at
N=4M, which made the two per-round partition lookups cost MORE than the
histogram matmul itself (the reference does these as random-access loads,
dense_bin.hpp:67-120; TPU has no fast vector gather).  A one-hot matmul
(`one_hot(ids) @ table`) runs the same lookup on the MXU in ~5 ms and is
EXACT: each output row sums exactly one non-zero product, so any f32 table
value round-trips bit-for-bit under HIGHEST precision.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

_CHUNK = 1 << 17


@functools.partial(jax.jit, static_argnames=("num_slots",))
def table_lookup(tables: jax.Array, ids: jax.Array, *,
                 num_slots: int) -> jax.Array:
    """tables [T, S] f32, ids [N] int32 in [0, num_slots) → [T, N] f32.

    S must be >= num_slots; slots >= num_slots are never selected.  Exact
    for any f32 table values (see module docstring).
    """
    T, S = tables.shape
    N = ids.shape[0]
    C = min(_CHUNK, N)
    nch = (N + C - 1) // C
    idp = jnp.pad(ids, (0, nch * C - N)) if nch * C > N else ids

    def body(_, idc):
        oh = (idc[None, :] == jax.lax.broadcasted_iota(
            jnp.int32, (S, 1), 0)).astype(jnp.float32)        # [S, C]
        r = jax.lax.dot(tables, oh,
                        precision=jax.lax.Precision.HIGHEST)  # [T, C]
        return None, r

    _, out = jax.lax.scan(body, None, idp.reshape(nch, C))
    return out.transpose(1, 0, 2).reshape(T, nch * C)[:, :N]


def select_bin_by_feature(bins_fn: jax.Array, fi: jax.Array) -> jax.Array:
    """Per-row bin of that row's feature: bins_fn [F, N] int, fi [N] int32
    → [N] int32 (rows whose fi matches no feature yield 0).

    A single fused compare/select/reduce pass over the feature axis — the
    alternative, a minor-axis 2-D gather `bins[fi, rows]`, serializes on
    TPU just like the table gathers above.
    """
    F = bins_fn.shape[0]
    return jnp.sum(jnp.where(fi[None, :] == jax.lax.broadcasted_iota(
        jnp.int32, (F, 1), 0), bins_fn.astype(jnp.int32), 0), axis=0)
