"""Small-table row lookups as one-hot matmuls.

XLA:TPU lowers `table[ids]` for a [N]-sized `ids` to a serialized gather
that runs at well under 1 GB/s — measured 65 ms for a 256-entry lookup at
N=4M, which made the two per-round partition lookups cost MORE than the
histogram matmul itself (the reference does these as random-access loads,
dense_bin.hpp:67-120; TPU has no fast vector gather).  A one-hot matmul
(`one_hot(ids) @ table`) runs the same lookup on the MXU in ~5 ms and is
EXACT: each output row sums exactly one non-zero product, so any f32 table
value round-trips bit-for-bit under HIGHEST precision.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

_CHUNK = 1 << 17
_PALLAS_CHUNK = 8192


def _lookup_kernel(tbl_ref, ids_ref, out_ref, *, S: int):
    ids = ids_ref[0, :]                                      # [Ck] i32
    oh = (ids[None, :] == jax.lax.broadcasted_iota(
        jnp.int32, (S, 1), 0)).astype(jnp.float32)           # [S, Ck]
    out_ref[:, :] = jnp.dot(tbl_ref[:, :], oh,
                            preferred_element_type=jnp.float32,
                            precision=jax.lax.Precision.HIGHEST)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _lookup_pallas(tables: jax.Array, ids: jax.Array,
                   interpret: bool = False) -> jax.Array:
    """Fused lookup: the [S, Ck] one-hot lives only in VMEM, so HBM
    traffic is ids in + [T, N] out (the XLA scan formulation writes the
    one-hot through HBM — ~13 ms per 10.5M-row lookup at S=256)."""
    from jax.experimental import pallas as pl

    T, S = tables.shape
    N = ids.shape[0]
    if T < 8:                       # sublane-align the table rows
        tables = jnp.pad(tables, ((0, 8 - T), (0, 0)))
    # VMEM: S*Ck*4 one-hot + blocks; keep ~8 MB => Ck 8192 at S<=256
    Ck = min(N, max(512, (int(8e6) // (4 * S)) // 128 * 128))
    if N % Ck:
        ids = jnp.pad(ids, (0, Ck - N % Ck), constant_values=-1)
    C = ids.shape[0]
    out = pl.pallas_call(
        functools.partial(_lookup_kernel, S=S),
        out_shape=jax.ShapeDtypeStruct((8, C), jnp.float32),
        grid=(C // Ck,),
        in_specs=[pl.BlockSpec((8, S), lambda k: (0, 0)),
                  pl.BlockSpec((1, Ck), lambda k: (0, k))],
        out_specs=pl.BlockSpec((8, Ck), lambda k: (0, k)),
        interpret=interpret,
    )(tables, ids[None, :])
    return out[:T, :N]


@functools.partial(jax.jit, static_argnames=("num_slots",))
def table_lookup(tables: jax.Array, ids: jax.Array, *,
                 num_slots: int) -> jax.Array:
    """tables [T, S] f32, ids [N] int32 in [0, num_slots) → [T, N] f32.

    S must be >= num_slots; slots >= num_slots are never selected (ids
    outside [0, S) select nothing and yield 0.0).  Exact for any f32
    table values (see module docstring).  On TPU the fused pallas path
    keeps the one-hot in VMEM; the XLA scan is the fallback for huge
    tables and other backends.
    """
    T, S = tables.shape
    N = ids.shape[0]
    if (jax.default_backend() == "tpu" and S <= 2048
            and T <= 8 and N >= _PALLAS_CHUNK):
        return _lookup_pallas(tables, ids)
    C = min(_CHUNK, N)
    nch = (N + C - 1) // C
    idp = jnp.pad(ids, (0, nch * C - N)) if nch * C > N else ids

    def body(_, idc):
        oh = (idc[None, :] == jax.lax.broadcasted_iota(
            jnp.int32, (S, 1), 0)).astype(jnp.float32)        # [S, C]
        r = jax.lax.dot(tables, oh,
                        precision=jax.lax.Precision.HIGHEST)  # [T, C]
        return None, r

    _, out = jax.lax.scan(body, None, idp.reshape(nch, C))
    return out.transpose(1, 0, 2).reshape(T, nch * C)[:, :N]


def select_bin_by_feature(bins_fn: jax.Array, fi: jax.Array) -> jax.Array:
    """Per-row bin of that row's feature: bins_fn [F, N] int, fi [N] int32
    → [N] int32 (rows whose fi matches no feature yield 0).

    A single fused compare/select/reduce pass over the feature axis — the
    alternative, a minor-axis 2-D gather `bins[fi, rows]`, serializes on
    TPU just like the table gathers above.
    """
    F = bins_fn.shape[0]
    return jnp.sum(jnp.where(fi[None, :] == jax.lax.broadcasted_iota(
        jnp.int32, (F, 1), 0), bins_fn.astype(jnp.int32), 0), axis=0)
