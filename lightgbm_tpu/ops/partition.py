"""Fused row partition for the rounds learner.

One boosting round reassigns every row: look up its leaf's split
(feature, threshold, is-categorical, new-leaf id), read the row's bin of
that feature, and move the row right when the split sends it there.  The
reference does this as random-access loads per row
(data_partition.hpp:80-130, dense_bin.hpp:67-120); XLA:TPU expresses it
as two one-hot matmuls plus elementwise selects (ops/lookup.py), which
materialize [N, ·] one-hots in HBM — measured 41 ms/round at the
north-star shape (profile_hotpath_measured.json), a quarter of the
iteration once the histogram kernels are narrow.

The pallas kernel fuses the whole step in VMEM per row-chunk:

- ONE int8 [8, S] @ [S, Ck] matmul performs ALL table lookups: the
  slot one-hot is built with the narrow int8 compare (ids - 128, exact
  while S <= 256 — same window argument as ops/histogram._packed_onehot)
  and the table rows carry threshold-128, is-cat|default-left flags,
  new-leaf-128, the in-range window bounds lo-128 / hi-128, and the
  split column as two base-128 digits (c_hi, c_lo), every entry in
  int8 range, each product exact, int32 accumulation of a single
  non-zero per column.
- the row's bin of its split column is a compare-reduce over the
  feature axis of the SAME bins block the histogram kernel streams
  (no [N, F] one-hot ever leaves VMEM).
- the left/right decision and the new leaf id are elementwise.

With Exclusive Feature Bundling the stored column packs several original
features; the per-leaf table then carries the STORE-space predicate from
ops/split.bundle_predicate_params: rows inside the feature's slot window
[lo, hi] compare against T, rows outside sit at the feature's default
bin and take the precomputed default-left bit.  An unbundled split is
the degenerate window [0, inf) — the same kernel serves both.

HBM traffic collapses to: bins read once, lid read once, lid2 written
once.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

import os as _os

from .histogram import MASKED_HIST_CHUNK
from .lookup import table_lookup, select_bin_by_feature

# kill-switch for on-chip A/B: 0 routes every call to the XLA composition
FUSED_PARTITION = _os.environ.get("LGBT_FUSED_PARTITION", "1") != "0"


def disable_fused_partition():
    """Runtime fallback (see histogram.disable_narrow_onehot): flip the
    flag and drop compiled traces; callers rebuild their jits."""
    global FUSED_PARTITION
    FUSED_PARTITION = False
    _partition_pallas.clear_cache()


def _augment_tbl(tbl: jax.Array) -> jax.Array:
    """Accept the legacy [4, S] (feature, threshold, is-cat, new-leaf)
    table and pad it to the 7-row store-space form with the degenerate
    always-in-range window (lo=0, hi1=2^30, dl=0)."""
    if tbl.shape[0] >= 7:
        return tbl
    S = tbl.shape[1]
    return jnp.concatenate([
        tbl,
        jnp.zeros((1, S), tbl.dtype),                       # lo
        jnp.full((1, S), float(1 << 30), tbl.dtype),        # hi1
        jnp.zeros((1, S), tbl.dtype)])                      # dl


def _partition_kernel(tbl_ref, gb_ref, lid_ref, out_ref, *, S: int,
                      bin_offset: int):
    """tbl_ref [8, S] int8 rows (c_hi, c_lo, T-128, cat, nli-128, lo-128,
    hi1-128, dl); gb_ref [1, F, Ck] int bins (int8 holds value-128 when
    bin_offset); lid_ref/out_ref [1, Ck] int32."""
    lidv = lid_ref[0, :]                                     # [Ck] i32
    lid8 = (lidv - 128).astype(jnp.int8)
    iota8 = (jax.lax.broadcasted_iota(jnp.int32, (S, 1), 0)
             - 128).astype(jnp.int8)
    oh = jnp.where(iota8 == lid8[None, :], jnp.int8(1), jnp.int8(0))
    r = jnp.dot(tbl_ref[:, :], oh,
                preferred_element_type=jnp.int32)            # [8, Ck]
    fi = r[0] * 128 + r[1]
    ti = r[2] + 128
    ci = r[3] > 0
    nli = r[4] + 128
    lo = r[5] + 128
    hi1 = r[6] + 128
    dl = r[7] > 0

    gb = gb_ref[0]                                           # [F, Ck]
    F = gb.shape[0]
    iof = jax.lax.broadcasted_iota(jnp.int32, (F, 1), 0)
    # exactly one feature row matches per column, so the sum IS the
    # selected bin; padded feature rows are never selected (fi < F)
    vi = jnp.sum(jnp.where(fi[None, :] == iof, gb.astype(jnp.int32), 0),
                 axis=0) + bin_offset                        # [Ck]
    gl = jnp.where(ci, vi == ti, vi <= ti)
    gl = jnp.where((vi >= lo) & (vi <= hi1), gl, dl)
    out_ref[0, :] = jnp.where((nli > 0) & ~gl, nli, lidv)


@functools.partial(jax.jit, static_argnames=("num_slots", "interpret"))
def _partition_pallas(tbl8, gb_t, lid, *, num_slots: int,
                      interpret: bool = False):
    from jax.experimental import pallas as pl

    F, C = gb_t.shape
    bin_offset = 128 if gb_t.dtype == jnp.int8 else 0
    isz = jnp.dtype(gb_t.dtype).itemsize
    # sublane-align the feature axis (int8 tiles are (32, 128)); padded
    # feature rows are never selected — fi always names a real feature
    sub = 32 if isz == 1 else 8
    if F % sub:
        gb_t = jnp.pad(gb_t, ((0, sub - F % sub), (0, 0)))
        F = gb_t.shape[0]
    # VMEM model: bins block F*Ck*isz, its int32 widen F*Ck*4, the
    # [S, Ck] one-hot — keep under ~10 MB
    Ck = min(C, MASKED_HIST_CHUNK)
    per_row = F * (isz + 4) + num_slots
    Ck = min(Ck, max(512, (int(10e6) // per_row) // 128 * 128))
    if C % Ck:
        pad = Ck - C % Ck
        gb_t = jnp.pad(gb_t, ((0, 0), (0, pad)))
        # pad rows sit in slot 0; their lid2 is discarded by the caller
        lid = jnp.pad(lid, (0, pad))
        C += pad
    grid = (C // Ck,)
    out = pl.pallas_call(
        functools.partial(_partition_kernel, S=num_slots,
                          bin_offset=bin_offset),
        out_shape=jax.ShapeDtypeStruct((1, C), jnp.int32),
        grid=grid,
        in_specs=[
            pl.BlockSpec((8, num_slots), lambda k: (0, 0)),
            pl.BlockSpec((1, F, Ck), lambda k: (0, 0, k)),
            pl.BlockSpec((1, Ck), lambda k: (0, k)),
        ],
        out_specs=pl.BlockSpec((1, Ck), lambda k: (0, k)),
        interpret=interpret,
    )(tbl8, gb_t[None], lid[None, :])
    return out[0]


def partition_rows(bins_fn: jax.Array, leaf_id: jax.Array,
                   tbl: jax.Array, *, num_slots: int, backend: str = "xla",
                   num_bins_padded: int = 0,
                   interpret: bool = False) -> jax.Array:
    """New leaf id per row after this round's splits.

    bins_fn [F, N] int STORE bins (int8 = value-128 storage); leaf_id [N]
    int32 in [0, num_slots-1); tbl [7, num_slots] f32 rows
    (store column, threshold T, is-categorical, new leaf id, window lo,
    window hi inclusive, default-left) indexed by leaf — the store-space
    predicate of ops/split.bundle_predicate_params.  The legacy [4, S]
    layout is accepted and padded with the always-in-range window.  Row
    values of non-splitting leaves must be 0 (new leaf 0 means "stay",
    leaf 0 is never a NEW leaf).

    Routes to the fused pallas kernel when the int8 encodings are exact
    (slots <= 256, thresholds < 256, column ids < 2^14 i.e. two base-128
    digits); otherwise composes the XLA one-hot lookups.
    """
    tbl = _augment_tbl(tbl)
    F = bins_fn.shape[0]
    # the kernel holds ALL F feature rows (bins + their int32 widen) per
    # block — the VMEM model must admit Ck >= 512, which bounds F at
    # ~3.8k int8 / ~2.4k int32 features; larger goes to the XLA path
    isz = jnp.dtype(bins_fn.dtype).itemsize
    f_fits = 512 * (F * (isz + 4) + 256) <= int(10e6)
    fits = (FUSED_PARTITION and backend == "pallas" and num_slots <= 256
            and 0 < num_bins_padded <= 256 and f_fits)
    if not fits:
        r = table_lookup(tbl, leaf_id, num_slots=num_slots)
        fi = r[0].astype(jnp.int32)
        ti = r[1].astype(jnp.int32)
        ci = r[2] > 0
        nli = r[3].astype(jnp.int32)
        lo = r[4].astype(jnp.int32)
        hi1 = r[5].astype(jnp.int32)
        dl = r[6] > 0
        off = 128 if bins_fn.dtype == jnp.int8 else 0
        vi = select_bin_by_feature(bins_fn, fi) + off
        gl = jnp.where(ci, vi == ti, vi <= ti)
        gl = jnp.where((vi >= lo) & (vi <= hi1), gl, dl)
        return jnp.where((nli > 0) & ~gl, nli, leaf_id)

    S = 256 if num_slots > 128 else 128          # lane-pad the slot axis
    # pad the slot axis BEFORE the -128 shifts: padded slots must decode
    # to thr=0/nli=0 ("stay"), matching the XLA path's zero table rows —
    # padding the shifted rows with 0 would decode to thr=128/nli=128 and
    # silently MOVE any out-of-contract leaf id to leaf 128
    pad = ((0, S - num_slots),)
    feat = jnp.pad(tbl[0].astype(jnp.int32), pad)
    thr = jnp.pad(tbl[1].astype(jnp.int32), pad)
    cat = jnp.pad(tbl[2].astype(jnp.int32), pad)
    nli = jnp.pad(tbl[3].astype(jnp.int32), pad)
    lo = jnp.pad(tbl[4].astype(jnp.int32), pad)
    # store bins are < 256 on this path, so clamping the degenerate
    # 2^30 window top to 255 keeps the predicate identical in int8
    hi1 = jnp.clip(jnp.pad(tbl[5].astype(jnp.int32), pad), 0, 255)
    dl = jnp.pad(tbl[6].astype(jnp.int32), pad)
    tbl8 = jnp.stack([feat // 128, feat % 128, thr - 128, cat, nli - 128,
                      lo - 128, hi1 - 128, dl]).astype(jnp.int8)
    N = leaf_id.shape[0]
    return _partition_pallas(tbl8, bins_fn, leaf_id, num_slots=S,
                             interpret=interpret)[:N]


def partition_rows_sparse(cols: jax.Array, binsv: jax.Array,
                          zero_bin: jax.Array, leaf_id: jax.Array,
                          tbl: jax.Array, *, num_slots: int) -> jax.Array:
    """partition_rows over the CSR/ELL sparse store (docs/Sparse.md).

    cols/binsv [N, R] per-row (store column, bin) entries (col sentinel
    >= C marks an empty slot); zero_bin [C] int32.  The row's bin of
    its leaf's split column is an ELL probe — at most R compares per
    row, nnz-scaled like the sparse histogram — falling back to the
    column's zero bin when the row stores no entry there.  Table
    semantics match partition_rows exactly (new-leaf 0 = stay)."""
    tbl = _augment_tbl(tbl)
    r = table_lookup(tbl, leaf_id, num_slots=num_slots)
    fi = r[0].astype(jnp.int32)
    ti = r[1].astype(jnp.int32)
    ci = r[2] > 0
    nli = r[3].astype(jnp.int32)
    lo = r[4].astype(jnp.int32)
    hi1 = r[5].astype(jnp.int32)
    dl = r[6] > 0
    hit = cols == fi[:, None]                            # [N, R]
    vi = jnp.sum(jnp.where(hit, binsv, 0), axis=1)
    C = zero_bin.shape[0]
    zb = jnp.maximum(zero_bin[jnp.clip(fi, 0, C - 1)], 0)
    vi = jnp.where(jnp.any(hit, axis=1), vi, zb)
    gl = jnp.where(ci, vi == ti, vi <= ti)
    gl = jnp.where((vi >= lo) & (vi <= hi1), gl, dl)
    return jnp.where((nli > 0) & ~gl, nli, leaf_id)
