"""On-device ensemble prediction.

The reference predicts row-by-row with a pointer-chasing node walk
(/root/reference/include/LightGBM/tree.h:217-241, gbdt.cpp:874-923).  On
TPU that becomes a vectorized breadth-parallel walk: all rows advance one
level per step (`lax.fori_loop` over the tree depth), with gathers instead
of pointer dereferences, vmapped over the stacked trees of the ensemble.
"""
from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np


class TreeStack(NamedTuple):
    """Ensemble as stacked flat-node arrays, padded to the widest tree.
    Child convention matches tree.h: internal >= 0, leaves as ~leaf."""
    split_feature: jax.Array   # [T, M-1] int32 (inner feature index)
    threshold: jax.Array       # [T, M-1] f32 — bin id for binned input,
                               #               raw value for raw input
    decision_type: jax.Array   # [T, M-1] int32 (0 numerical, 1 categorical)
    left_child: jax.Array      # [T, M-1] int32
    right_child: jax.Array     # [T, M-1] int32
    leaf_value: jax.Array      # [T, M] f32
    num_leaves: jax.Array      # [T] int32


def stack_trees(trees, binned: bool) -> TreeStack:
    """Stack host Tree objects into one padded TreeStack (device)."""
    m = max(max(t.max_leaves for t in trees), 2)
    T = len(trees)
    sf = np.zeros((T, m - 1), np.int32)
    th = np.zeros((T, m - 1), np.float32)
    dc = np.zeros((T, m - 1), np.int32)
    lc = np.full((T, m - 1), -1, np.int32)
    rc = np.full((T, m - 1), -1, np.int32)
    lv = np.zeros((T, m), np.float32)
    nl = np.zeros(T, np.int32)
    for i, t in enumerate(trees):
        n = t.num_leaves
        nl[i] = n
        lv[i, :n] = t.leaf_value[:n]
        if n < 2:
            continue
        k = n - 1
        sf[i, :k] = (t.split_feature_inner[:k] if binned
                     else t.split_feature[:k])
        th[i, :k] = (t.threshold_in_bin[:k].astype(np.float32) if binned
                     else t.threshold[:k].astype(np.float32))
        dc[i, :k] = t.decision_type[:k]
        lc[i, :k] = t.left_child[:k]
        rc[i, :k] = t.right_child[:k]
    return TreeStack(*map(jnp.asarray, (sf, th, dc, lc, rc, lv, nl)))


def _walk_one_tree(sf, th, dc, lc, rc, lv, nl, Xf, depth: int) -> jax.Array:
    """Leaf values for every row of one tree ([N] f32): all rows advance
    one level per step, gathers instead of pointer dereferences."""
    n0 = jnp.where(nl < 2, jnp.int32(-1), jnp.int32(0))  # stumps: leaf 0
    node = jnp.full(Xf.shape[0], n0, jnp.int32)

    def step(_, node):
        safe = jnp.maximum(node, 0)
        f = sf[safe]
        v = jnp.take_along_axis(Xf, f[:, None], axis=1)[:, 0]
        t = th[safe]
        cat = dc[safe] == 1
        # categorical: int truncation compare, matching the host walk
        # (tree.py predict_leaf_index: v.astype(int64) == thr int64)
        gl = jnp.where(cat,
                       v.astype(jnp.int32) == t.astype(jnp.int32),
                       v <= t)
        nxt = jnp.where(gl, lc[safe], rc[safe])
        return jnp.where(node >= 0, nxt, node)

    node = jax.lax.fori_loop(0, depth, step, node)
    leaf = jnp.where(node < 0, ~node, 0)
    return lv[leaf]


@functools.partial(jax.jit, static_argnames=("depth",))
def predict_trees(stack: TreeStack, X: jax.Array, *, depth: int) -> jax.Array:
    """Sum of tree outputs for every row.

    X : [N, F] — binned ids (f32-comparable) or raw feature values,
        matching how the stack was built.
    depth : static upper bound on tree depth (#levels to walk).
    Returns [N] f32.
    """
    Xf = X.astype(jnp.float32)

    def one_tree(sf, th, dc, lc, rc, lv, nl):
        return _walk_one_tree(sf, th, dc, lc, rc, lv, nl, Xf, depth)

    vals = jax.vmap(one_tree)(*stack)          # [T, N]
    return jnp.sum(vals, axis=0)


def ensemble_raw(stacks, X: jax.Array, *, depths) -> jax.Array:
    """Raw per-class scores for a multi-class ensemble ([K, N] f32).

    `stacks` is one TreeStack (or None for an untrained class — its row
    stays zero, matching GBDT._predict_raw_device) per class; `depths`
    the matching static walk depths.  Traceable: the serving runtime
    AOT-compiles this once per (generation, row bucket, output kind).
    """
    Xf = X.astype(jnp.float32)
    outs = []
    for stack, depth in zip(stacks, depths):
        if stack is None:
            outs.append(jnp.zeros(Xf.shape[0], jnp.float32))
            continue

        def one_tree(sf, th, dc, lc, rc, lv, nl, _d=depth):
            return _walk_one_tree(sf, th, dc, lc, rc, lv, nl, Xf, _d)

        outs.append(jnp.sum(jax.vmap(one_tree)(*stack), axis=0))
    return jnp.stack(outs)
