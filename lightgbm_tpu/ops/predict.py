"""On-device ensemble prediction.

The reference predicts row-by-row with a pointer-chasing node walk
(/root/reference/include/LightGBM/tree.h:217-241, gbdt.cpp:874-923).  On
TPU that becomes a vectorized breadth-parallel walk: all rows advance one
level per step (`lax.fori_loop` over the tree depth), with gathers instead
of pointer dereferences, vmapped over the stacked trees of the ensemble.

Two kernels implement that walk, selected by ``predict_kernel``:

- ``walk`` — the original shape: one `_walk_one_tree` per tree, vmapped
  over each class's TreeStack, one program per class
  (`predict_trees` / `ensemble_raw`).
- ``tensorized`` — the Booster-accelerator shape (arXiv:2011.02022):
  EVERY tree of EVERY class flattened into ONE padded ``[T, nodes]``
  SoA whose per-node record (feature, threshold, decision, children,
  default-left) is packed into a single trailing lane axis, so each
  depth level costs ONE batched record gather + ONE feature gather +
  selects for all N rows x T trees at once — `depth` loop iterations
  total for the whole ensemble, and per-class sums fall out of one
  sorted segment-sum.  A binned-input variant
  (`predict_ensemble_binned`) walks the int bin store directly with
  in-bin thresholds (integer compares, no float thresholding), including
  the EFB packed-slot remap, so whole-model replay onto a ScoreUpdater
  is `depth` passes instead of `len(trees)` sequential tree walks.  The
  serving request path runs the same walk on ingress-quantized uint8
  buffers (`predict_ensemble_quantized`, serve_quantize=binned): the
  fixed-point traversal of the Booster accelerator applied end-to-end,
  bitwise-identical to the raw kernel by construction of the quantizer
  (lightgbm_tpu/quantize.py).
"""
from __future__ import annotations

import functools
import os
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..config import COSTACK_KERNELS, PREDICT_KERNELS


def resolve_predict_kernel(kernel: str = "auto") -> str:
    """Resolve the ``predict_kernel`` dial to a concrete kernel.

    ``auto`` picks ``tensorized``: it traverses the whole ensemble in
    `depth` fused steps on every backend, strictly fewer dispatches and
    gathers than the per-class walk (which it matches bitwise on fp32
    dyadic leaf values — tests/test_predict_kernel.py).  ``walk`` stays
    reachable as the A/B baseline and conservative fallback.
    """
    if kernel not in PREDICT_KERNELS:
        raise ValueError(f"unknown predict_kernel: {kernel!r}; "
                         f"use one of {PREDICT_KERNELS}")
    return "tensorized" if kernel == "auto" else kernel


# above this total stacked tree count, even launch-bound accelerators
# go compute-bound on the walk-all grouped traversal: the per-level
# record gather over all T_total trees dwarfs the one launch that
# co-stacking saves, so `auto` switches to the segment-gathered walk.
# Default for the validated `costack_segment_trees` Config key; direct
# resolve_costack_kernel callers inherit it when they pass no override.
COSTACK_SEGMENT_TREES = 4096


def resolve_costack_kernel(kernel: str = "auto", *,
                           total_trees: int = 0,
                           segment_trees: int = 0) -> str:
    """Resolve the ``costack_kernel`` dial to a concrete grouped
    traversal (config.COSTACK_KERNELS).

    ``auto`` picks ``segment`` on compute-bound backends (CPU: node
    math scales with the trees walked, so walking all T_total stacked
    trees costs ~G x a solo tenant per row) and on accelerators once
    the group's total stacked tree count crosses the switch point;
    ``stacked`` stays the pick where launch overhead dominates (the TPU
    premise — surplus trees ride a gather-bound depth loop for free).
    Both variants are bitwise-identical to per-tenant dispatch
    (tests/test_costack.py), so the dial is purely a cost model.

    ``segment_trees`` (<= 0 = COSTACK_SEGMENT_TREES) is the Config key
    ``costack_segment_trees``; the LIGHTGBM_TPU_COSTACK_SEGMENT_TREES
    environment override — read here, at resolve time — wins over both
    for fleet-wide retunes without a config rollout.
    """
    if kernel not in COSTACK_KERNELS:
        raise ValueError(f"unknown costack_kernel: {kernel!r}; "
                         f"use one of {COSTACK_KERNELS}")
    if kernel != "auto":
        return kernel
    thresh = int(segment_trees) if segment_trees and segment_trees > 0 \
        else COSTACK_SEGMENT_TREES
    env = os.environ.get("LIGHTGBM_TPU_COSTACK_SEGMENT_TREES")
    if env:
        try:
            thresh = max(1, int(env))
        except ValueError:
            raise ValueError(
                "LIGHTGBM_TPU_COSTACK_SEGMENT_TREES must be an integer, "
                f"got {env!r}")
    if jax.default_backend() not in ("tpu", "gpu"):
        return "segment"
    return "segment" if total_trees >= thresh else "stacked"


class TreeStack(NamedTuple):
    """Ensemble as stacked flat-node arrays, padded to the widest tree.
    Child convention matches tree.h: internal >= 0, leaves as ~leaf."""
    split_feature: jax.Array   # [T, M-1] int32 (inner feature index)
    threshold: jax.Array       # [T, M-1] f32 — bin id for binned input,
                               #               raw value for raw input
    decision_type: jax.Array   # [T, M-1] int32 (0 numerical, 1 categorical)
    left_child: jax.Array      # [T, M-1] int32
    right_child: jax.Array     # [T, M-1] int32
    leaf_value: jax.Array      # [T, M] f32
    num_leaves: jax.Array      # [T] int32


def stack_trees(trees, binned: bool) -> TreeStack:
    """Stack host Tree objects into one padded TreeStack (device)."""
    m = max(max(t.max_leaves for t in trees), 2)
    T = len(trees)
    sf = np.zeros((T, m - 1), np.int32)
    th = np.zeros((T, m - 1), np.float32)
    dc = np.zeros((T, m - 1), np.int32)
    lc = np.full((T, m - 1), -1, np.int32)
    rc = np.full((T, m - 1), -1, np.int32)
    lv = np.zeros((T, m), np.float32)
    nl = np.zeros(T, np.int32)
    for i, t in enumerate(trees):
        n = t.num_leaves
        nl[i] = n
        lv[i, :n] = t.leaf_value[:n]
        if n < 2:
            continue
        k = n - 1
        sf[i, :k] = (t.split_feature_inner[:k] if binned
                     else t.split_feature[:k])
        th[i, :k] = (t.threshold_in_bin[:k].astype(np.float32) if binned
                     else t.threshold[:k].astype(np.float32))
        dc[i, :k] = t.decision_type[:k]
        lc[i, :k] = t.left_child[:k]
        rc[i, :k] = t.right_child[:k]
    return TreeStack(*map(jnp.asarray, (sf, th, dc, lc, rc, lv, nl)))


def _walk_one_tree(sf, th, dc, lc, rc, lv, nl, Xf, depth: int) -> jax.Array:
    """Leaf values for every row of one tree ([N] f32): all rows advance
    one level per step, gathers instead of pointer dereferences."""
    n0 = jnp.where(nl < 2, jnp.int32(-1), jnp.int32(0))  # stumps: leaf 0
    node = jnp.full(Xf.shape[0], n0, jnp.int32)

    def step(_, node):
        safe = jnp.maximum(node, 0)
        f = sf[safe]
        v = jnp.take_along_axis(Xf, f[:, None], axis=1)[:, 0]
        t = th[safe]
        cat = dc[safe] == 1
        # categorical: int truncation compare with the host walk's
        # explicit finite mask (tree.py predict_leaf_index) — a bare
        # int cast of NaN is backend-defined and could match category 0
        finite = jnp.isfinite(v)
        vi = jnp.where(finite, v, -1.0).astype(jnp.int32)
        gl = jnp.where(cat, finite & (vi == t.astype(jnp.int32)), v <= t)
        nxt = jnp.where(gl, lc[safe], rc[safe])
        return jnp.where(node >= 0, nxt, node)

    node = jax.lax.fori_loop(0, depth, step, node)
    leaf = jnp.where(node < 0, ~node, 0)
    return lv[leaf]


@functools.partial(jax.jit, static_argnames=("depth",))
def predict_trees(stack: TreeStack, X: jax.Array, *, depth: int) -> jax.Array:
    """Sum of tree outputs for every row.

    X : [N, F] — binned ids (f32-comparable) or raw feature values,
        matching how the stack was built.
    depth : static upper bound on tree depth (#levels to walk).
    Returns [N] f32.
    """
    Xf = X.astype(jnp.float32)

    def one_tree(sf, th, dc, lc, rc, lv, nl):
        return _walk_one_tree(sf, th, dc, lc, rc, lv, nl, Xf, depth)

    vals = jax.vmap(one_tree)(*stack)          # [T, N]
    return jnp.sum(vals, axis=0)


def ensemble_raw(stacks, X: jax.Array, *, depths) -> jax.Array:
    """Raw per-class scores for a multi-class ensemble ([K, N] f32).

    `stacks` is one TreeStack (or None for an untrained class — its row
    stays zero, matching GBDT._predict_raw_device) per class; `depths`
    the matching static walk depths.  Traceable: the serving runtime
    AOT-compiles this once per (generation, row bucket, output kind).
    """
    Xf = X.astype(jnp.float32)
    outs = []
    for stack, depth in zip(stacks, depths):
        if stack is None:
            outs.append(jnp.zeros(Xf.shape[0], jnp.float32))
            continue

        def one_tree(sf, th, dc, lc, rc, lv, nl, _d=depth):
            return _walk_one_tree(sf, th, dc, lc, rc, lv, nl, Xf, _d)

        outs.append(jnp.sum(jax.vmap(one_tree)(*stack), axis=0))
    return jnp.stack(outs)


# ----------------------------------------------------------------------
# tensorized ensemble traversal (predict_kernel=tensorized)
# ----------------------------------------------------------------------

# packed node-record lane order of EnsembleStack.nodes (one trailing lane
# axis so each depth level fetches ALL per-node fields with ONE gather of
# a contiguous record, instead of five scattered gathers):
#   raw stacks    (f32):       feat, threshold, is_cat, left, right
#   binned stacks (i16/i32):   feat, threshold_bin, decision, left, right
# child ids / feature ids are exact in f32 (|v| < 2^24, num_leaves caps
# far below that), so the raw record can stay one dtype.  Binned stacks
# narrow the whole record to int16 whenever every lane fits — half the
# per-level record-gather bytes on the serving request path.  NaN/missing
# routing needs no lane: raw kernels send NaN right (v <= t is False,
# categorical finite mask matches nothing) and the binned request path
# encodes missing as the quantizer's sentinel bin, which routes right
# through the same integer compares (lightgbm_tpu/quantize.py) — the
# never-populated default_left lane PR 7 reserved is gone.
_LANES = 5


class EnsembleStack(NamedTuple):
    """Every tree of every class as ONE padded [T, nodes] SoA.

    Trees are flattened class-major (class 0's trees in boosting order,
    then class 1's, ...), so ``class_id`` is sorted ascending and the
    per-class reduction is a sorted segment-sum.
    """
    nodes: jax.Array       # [T, M-1, _LANES] packed node records
    leaf_value: jax.Array  # [T, M] f32
    root: jax.Array        # [T] int32 — 0, or -1 for stumps (leaf 0)
    class_id: jax.Array    # [T] int32, sorted ascending


class PerfectEnsemble(NamedTuple):
    """Shallow numerical ensembles re-laid out as PERFECT binary trees of
    the ensemble depth: navigation is pure arithmetic (``2*node + 1 +
    go_right``), so the walk needs NO child gathers and no parked-row
    select — the Booster accelerator layout (arXiv:2011.02022 §3).

    A leaf grown at depth d < D acts as a filler subtree: every
    last-level record it covers carries the leaf's value in BOTH value
    lanes, so the routing through filler slots is irrelevant (any path
    lands on the same value).  The LAST level's records fuse the two
    child leaf values in, saving the separate leaf-value gather.

    BINNED perfect stacks (the serving request path under
    serve_quantize=binned) carry the INNER feature id and the in-bin
    threshold in the same f32 lanes: bin ids are < 2^24, so the f32
    compare against a quantized buffer is exactly the integer compare
    — one layout, both compare domains.
    """
    inner: jax.Array       # [T, 2^(D-1)-1, 2] f32: (feature, threshold)
    last: jax.Array        # [T, 2^(D-1), 4] f32: (feat, thr, lval, rval)
    class_id: jax.Array    # [T] int32, sorted ascending


class EnsembleMeta(NamedTuple):
    """Static (hashable) companions of an ensemble stack — jit cache keys."""
    depth: int             # levels to walk (max grown depth, >= 1)
    num_class: int         # K — rows of the [K, N] output
    any_cat: bool          # ensemble has categorical splits


class GroupMeta(NamedTuple):
    """Static companions of a cross-model SUPER-STACK: N tenants'
    ensembles concatenated along the tree axis (tenant-major, each
    tenant's trees class-major like its solo stack), scored for a mixed
    batch in ONE launch.  ``segments[g] = (start, stop)`` bounds tenant
    g's trees in the stack — static at trace time, so the per-tenant
    reductions slice and reduce exactly the tree set (same shape, same
    op) the tenant's SOLO stack would, which is what makes grouped
    scoring bitwise-identical to per-tenant dispatch."""
    depth: int             # levels to walk (max over every tenant)
    num_class: int         # K — shared by every tenant in the group
    any_cat: bool          # any tenant has categorical splits
    segments: tuple        # ((start, stop), ...) tree bounds per tenant


# perfect relayout budget: total value-slab slots (T * 2^depth) above
# which the padded-SoA traversal takes over — 2^22 slots is ~50 MB of
# node records at the default, far above the north-star 500-tree
# depth-8 shape (128k slots) and far below a pathological leaf-wise
# chain (depth 30+ would want 2^31 slots per tree).
PERFECT_SLOT_BUDGET = 1 << 22


def _ensemble_shape(flat, binned: bool):
    """(max-capacity leaves, walk depth, any_cat) over a class-major
    [(class, tree)] flatten — the ONE scan shared by `build_ensemble`'s
    layout choice and `stack_ensemble`'s meta, so the two can't
    desynchronize.  Binned stacks compare on `binned_decision_type`
    (trivial-feature categorical splits rebin to numerical
    sentinels)."""
    m = max(max(t.max_leaves for _, t in flat), 2)
    depth = 1
    any_cat = False
    for _, t in flat:
        if t.num_leaves < 2:
            continue
        depth = max(depth, t.max_depth_grown)
        k = t.num_leaves - 1
        dec = (getattr(t, "binned_decision_type", t.decision_type)
               if binned else t.decision_type)
        any_cat = any_cat or bool(np.any(dec[:k] == 1))
    return m, max(int(depth), 1), any_cat


def build_ensemble(trees_by_class, *, binned: bool = False,
                   layout: str = "auto"):
    """Build the tensorized-traversal stack for a whole model.

    Returns ``(stack, meta)`` where stack is a PerfectEnsemble (shallow,
    purely numerical raw ensembles within PERFECT_SLOT_BUDGET) or the
    general EnsembleStack SoA — both host numpy pytrees; callers
    `jax.device_put` them (per replica for the serving fleet).
    `predict_ensemble_any` dispatches on the type.
    """
    num_class = len(trees_by_class)
    flat = [(k, t) for k, trees in enumerate(trees_by_class) for t in trees]
    if not flat:
        raise ValueError("build_ensemble needs at least one tree")
    shape = _ensemble_shape(flat, binned)
    m, depth, any_cat = shape
    meta = EnsembleMeta(depth=depth, num_class=num_class, any_cat=any_cat)
    if layout not in ("auto", "perfect", "soa"):
        raise ValueError(f"unknown ensemble layout: {layout!r}")
    if layout == "auto":
        fits = len(flat) << depth <= PERFECT_SLOT_BUDGET
        layout = "perfect" if fits and not any_cat else "soa"
    if layout == "perfect":
        if any_cat:
            raise ValueError("perfect layout supports numerical "
                             "ensembles only")
        return _build_perfect(flat, meta, binned=binned)
    return stack_ensemble(trees_by_class, binned=binned, _shape=shape)


def _build_perfect(flat, meta: EnsembleMeta, binned: bool = False
                   ) -> tuple[PerfectEnsemble, EnsembleMeta]:
    D = meta.depth
    T = len(flat)
    half = 1 << (D - 1)
    inner = np.zeros((T, max(half - 1, 1), 2), np.float32)
    last = np.zeros((T, half, 4), np.float32)
    cls = np.zeros(T, np.int32)
    for i, (k, t) in enumerate(flat):
        cls[i] = k
        # binned stacks speak (inner feature, in-bin threshold) — both
        # < 2^24, exact in the f32 lanes
        sf = t.split_feature_inner if binned else t.split_feature
        th = t.threshold_in_bin if binned else t.threshold
        if t.num_leaves < 2:                 # stump: one giant filler
            last[i, :, 2] = last[i, :, 3] = np.float32(t.leaf_value[0])
            continue
        # iterative heap-order fill; a leaf met above the last level
        # replicates its value across every last-level slot it covers
        stack = [(0, 0, 0)]                  # (tree node, heap slot, level)
        while stack:
            node, slot, lvl = stack.pop()
            if lvl == D - 1:                 # last level: fuse child values
                local = slot - (half - 1)
                if node < 0:                 # leaf: value in both lanes
                    v = np.float32(t.leaf_value[~node])
                    last[i, local, 2] = last[i, local, 3] = v
                else:
                    lc = int(t.left_child[node])
                    rc = int(t.right_child[node])
                    # children at depth D of a depth-D tree are leaves
                    last[i, local, 0] = sf[node]
                    last[i, local, 1] = np.float32(th[node])
                    last[i, local, 2] = np.float32(t.leaf_value[~lc])
                    last[i, local, 3] = np.float32(t.leaf_value[~rc])
                continue
            if node < 0:                     # early leaf: filler subtree
                lo = (slot - ((1 << lvl) - 1)) << (D - 1 - lvl)
                hi = lo + (1 << (D - 1 - lvl))
                v = np.float32(t.leaf_value[~node])
                last[i, lo:hi, 2] = last[i, lo:hi, 3] = v
                continue
            inner[i, slot, 0] = sf[node]
            inner[i, slot, 1] = np.float32(th[node])
            stack.append((int(t.left_child[node]), 2 * slot + 1, lvl + 1))
            stack.append((int(t.right_child[node]), 2 * slot + 2, lvl + 1))
    return PerfectEnsemble(inner=inner, last=last, class_id=cls), meta


def stack_ensemble(trees_by_class, *, binned: bool, _shape=None
                   ) -> tuple[EnsembleStack, EnsembleMeta]:
    """Flatten per-class host Tree lists into one EnsembleStack (host
    numpy — callers `jax.device_put` the pytree, per replica for the
    serving fleet).  A class with no trees contributes no stack rows and
    its output row stays zero (segment-sum over an absent segment),
    matching `ensemble_raw`'s None handling.  Stumps ride along as
    root=-1 rows whose leaf 0 carries the constant.
    """
    num_class = len(trees_by_class)
    flat = [(k, t) for k, trees in enumerate(trees_by_class) for t in trees]
    if not flat:
        raise ValueError("stack_ensemble needs at least one tree")
    m, depth, any_cat = _shape or _ensemble_shape(flat, binned)
    meta = EnsembleMeta(depth=depth, num_class=num_class, any_cat=any_cat)
    nodes, lv, root, cls = _fill_stack(flat, m, binned)
    stack = EnsembleStack(nodes=_maybe_narrow(nodes, binned),
                          leaf_value=lv, root=root, class_id=cls)
    return stack, meta


def _fill_stack(flat, m: int, binned: bool):
    """The node/leaf fill over a class-major ``[(class, tree)]`` flatten
    — ONE loop shared by `stack_ensemble` and `stack_ensemble_group`, so
    a solo stack and a super-stack can never encode the same tree
    differently."""
    T = len(flat)
    dtype = np.int32 if binned else np.float32
    nodes = np.zeros((T, m - 1, _LANES), dtype)
    lv = np.zeros((T, m), np.float32)
    root = np.zeros(T, np.int32)
    cls = np.zeros(T, np.int32)
    for i, (k, t) in enumerate(flat):
        n = t.num_leaves
        cls[i] = k
        lv[i, :n] = t.leaf_value[:n]
        if n < 2:
            root[i] = -1                     # stump: every row is leaf 0
            continue
        knodes = n - 1
        if binned:
            dec = getattr(t, "binned_decision_type", t.decision_type)
            nodes[i, :knodes, 0] = t.split_feature_inner[:knodes]
            nodes[i, :knodes, 1] = t.threshold_in_bin[:knodes]
            nodes[i, :knodes, 2] = dec[:knodes]
        else:
            nodes[i, :knodes, 0] = t.split_feature[:knodes]
            nodes[i, :knodes, 1] = t.threshold[:knodes].astype(np.float32)
            nodes[i, :knodes, 2] = t.decision_type[:knodes]
        nodes[i, :knodes, 3] = t.left_child[:knodes]
        nodes[i, :knodes, 4] = t.right_child[:knodes]
    return nodes, lv, root, cls


def _maybe_narrow(nodes: np.ndarray, binned: bool) -> np.ndarray:
    """The integer record narrows to int16 whenever every lane fits
    (bins < 2^15, children/features < 2^15 — always, outside the
    trivial-feature rebin sentinels): half the record-gather bytes per
    depth level on the binned serving request path.  TPU only — CPU
    XLA's int16 gathers de-vectorize (measured 1.5x slower than the
    int32 record at the north-star shape)."""
    if binned and nodes.size and jax.default_backend() == "tpu" and \
            -0x8000 <= int(nodes.min()) and int(nodes.max()) < 0x8000:
        return nodes.astype(np.int16)
    return nodes


def stack_ensemble_group(members, *, binned: bool = False
                         ) -> tuple[EnsembleStack, GroupMeta]:
    """Co-stack N tenants' ensembles into ONE super-stack.

    ``members`` is a list of per-tenant ``trees_by_class`` lists (the
    same shape `stack_ensemble` takes), all with the SAME class count.
    Trees flatten tenant-major (each tenant's trees class-major, i.e.
    exactly its solo stack order) into one padded [T_total, nodes] SoA;
    ``meta.segments`` records each tenant's static tree bounds so
    `_grouped_sums` can reduce per tenant with the solo reduction.
    Node records pad to the WIDEST tree across the group and the walk
    runs to the DEEPEST tenant's depth — a parked row no-ops through
    surplus levels, so padding changes no routing decision, only the
    launch's node-record footprint (the grouping policy in
    serving/catalog.py bounds that waste by leaf-budget tier).
    """
    if not members:
        raise ValueError("stack_ensemble_group needs at least one member")
    ks = {len(tbc) for tbc in members}
    if len(ks) != 1:
        raise ValueError("co-stacked members must share num_class "
                         f"(got {sorted(ks)})")
    num_class = ks.pop()
    flat = []
    segments = []
    for tbc in members:
        start = len(flat)
        flat.extend((k, t) for k, trees in enumerate(tbc) for t in trees)
        if len(flat) == start:
            raise ValueError("every co-stacked member needs at least "
                             "one tree")
        segments.append((start, len(flat)))
    m, depth, any_cat = _ensemble_shape(flat, binned)
    meta = GroupMeta(depth=depth, num_class=num_class, any_cat=any_cat,
                     segments=tuple(segments))
    nodes, lv, root, cls = _fill_stack(flat, m, binned)
    stack = EnsembleStack(nodes=_maybe_narrow(nodes, binned),
                          leaf_value=lv, root=root, class_id=cls)
    return stack, meta


def _leaf_sums(stack: EnsembleStack, node: jax.Array, num_class: int
               ) -> jax.Array:
    """[K, N] per-class sums of the leaf values the [T, N] walk parked
    on.  class_id is sorted (class-major flatten), so the segment-sum
    reduces each class's trees in stack order — exact for fp32 dyadic
    leaf values in any order, and the same trees the walk kernel sums."""
    leaf = jnp.where(node < 0, ~node, 0)
    vals = jnp.take_along_axis(stack.leaf_value, leaf, axis=1)   # [T, N]
    if num_class == 1:
        return jnp.sum(vals, axis=0)[None]
    return jax.ops.segment_sum(vals, stack.class_id,
                               num_segments=num_class,
                               indices_are_sorted=True)


def _raw_decide(rec: jax.Array, v: jax.Array, any_cat: bool) -> jax.Array:
    """Go-left mask from packed raw node records and gathered feature
    values — THE numerical/categorical routing decision, shared by the
    full-stack walk (`_walk_raw_nodes`) and the segment-gathered walk
    (`_walk_raw_segment`) so the two can never disagree: numerical
    ``v <= t`` (NaN falls right), categorical int-truncation compare
    behind a finite mask."""
    t = rec[..., 1]
    gl = v <= t
    if any_cat:
        finite = jnp.isfinite(v)
        vi = jnp.where(finite, v, -1.0).astype(jnp.int32)
        gl = jnp.where(rec[..., 2] > 0,
                       finite & (vi == t.astype(jnp.int32)), gl)
    return gl


def _binned_decide(rec: jax.Array, bv: jax.Array,
                   any_cat: bool) -> jax.Array:
    """Go-left mask from packed BINNED node records and gathered bin
    ids — integer compares end to end, shared by `_walk_binned_nodes`
    and `_walk_binned_segment` (same contract as `_raw_decide`)."""
    t = rec[..., 1].astype(jnp.int32)
    if any_cat:
        return jnp.where(rec[..., 2] == 1, bv == t, bv <= t)
    return bv <= t


def _walk_raw_nodes(stack: EnsembleStack, Xf: jax.Array, meta
                    ) -> jax.Array:
    """The raw-feature ensemble walk itself: parked node per (tree, row)
    — [T, N] int32, leaves encoded as ~leaf.  Shared by the value kernel
    (`predict_ensemble`), the leaf-index kernel
    (`predict_ensemble_leaf`), and the grouped super-stack kernel
    (`predict_ensemble_grouped`) so they can never disagree on a routing
    decision.  Decision parity with `_walk_one_tree` is bitwise:
    numerical ``v <= t`` (NaN falls right), categorical int-truncation
    compare behind the host walk's finite mask (tree.py
    predict_leaf_index — non-finite never matches; a bare int cast of
    NaN is backend-defined)."""
    T = stack.nodes.shape[0]
    N = Xf.shape[0]
    rows = jnp.arange(N)[None, :]
    node = jnp.broadcast_to(stack.root[:, None], (T, N))

    def step(_, node):
        safe = jnp.maximum(node, 0)
        rec = jnp.take_along_axis(stack.nodes, safe[:, :, None], axis=1)
        f = rec[..., 0].astype(jnp.int32)
        v = Xf[rows, f]                                  # [T, N]
        gl = _raw_decide(rec, v, meta.any_cat)
        nxt = jnp.where(gl, rec[..., 3], rec[..., 4]).astype(jnp.int32)
        return jnp.where(node >= 0, nxt, node)

    return jax.lax.fori_loop(0, meta.depth, step, node)


@functools.partial(jax.jit, static_argnames=("meta",))
def predict_ensemble(stack: EnsembleStack, X: jax.Array, *,
                     meta: EnsembleMeta) -> jax.Array:
    """Raw per-class scores over raw feature values — [K, N] f32.

    All N rows x T trees advance one depth level per step: one batched
    record gather, one feature gather, one select.  `meta.depth` loop
    iterations total for the whole ensemble (the walk kernel runs a
    depth-loop per class and five gathers per level).
    """
    node = _walk_raw_nodes(stack, X.astype(jnp.float32), meta)
    return _leaf_sums(stack, node, meta.num_class)


@functools.partial(jax.jit, static_argnames=("meta",))
def predict_ensemble_perfect(stack: PerfectEnsemble, X: jax.Array, *,
                             meta: EnsembleMeta) -> jax.Array:
    """Raw per-class scores via perfect-layout traversal — [K, N] f32.

    Per level: ONE 8-byte record gather + ONE feature gather + a
    compare; the next node is arithmetic (no child gathers, no
    parked-row select).  The root level is peeled into a broadcast
    (every row reads record 0), and the last level's records carry both
    child leaf values, so the separate leaf-value gather disappears.
    Bitwise-identical routing to `_walk_one_tree` (same ``v <= t`` f32
    compare on the same thresholds).
    """
    Xf = X.astype(jnp.float32)
    T = stack.last.shape[0]
    N = Xf.shape[0]
    rows = jnp.arange(N)[None, :]
    depth = meta.depth

    def level(rec_slab, node):
        r = jnp.take_along_axis(rec_slab, node[:, :, None], axis=1)
        f = r[..., 0].astype(jnp.int32)
        gl = Xf[rows, f] <= r[..., 1]
        return r, gl

    if depth == 1:
        local = jnp.zeros((T, N), jnp.int32)
    else:
        # level 0: every row is at the root — broadcast, no gather
        f0 = stack.inner[:, 0, 0].astype(jnp.int32)
        gl0 = jnp.take(Xf, f0, axis=1).T <= stack.inner[:, 0, 1][:, None]
        node = 2 - gl0.astype(jnp.int32)

        def step(_, node):
            _, gl = level(stack.inner, node)
            return 2 * node + 2 - gl.astype(jnp.int32)

        node = jax.lax.fori_loop(1, depth - 1, step, node)
        local = node - ((1 << (depth - 1)) - 1)
    r, gl = level(stack.last, local)
    vals = jnp.where(gl, r[..., 2], r[..., 3])              # [T, N]
    if meta.num_class == 1:
        return jnp.sum(vals, axis=0)[None]
    return jax.ops.segment_sum(vals, stack.class_id,
                               num_segments=meta.num_class,
                               indices_are_sorted=True)


def predict_ensemble_any(stack, X: jax.Array, *,
                         meta: EnsembleMeta) -> jax.Array:
    """Layout dispatch (trace-time): PerfectEnsemble or EnsembleStack."""
    if isinstance(stack, PerfectEnsemble):
        return predict_ensemble_perfect(stack, X, meta=meta)
    return predict_ensemble(stack, X, meta=meta)


def sparse_bin_lookup(cols: jax.Array, binsv: jax.Array,
                      zero_bin: jax.Array, col: jax.Array) -> jax.Array:
    """Store bin id per requested column, straight off the ELL row
    segments — the traversal-side analog of the sparse partition probe
    (ops/partition.partition_rows_sparse): a stored (column, bin) entry
    answers directly, everything else answers the column's zero bin.

    cols/binsv: [N, R] ELL entries (col >= num_columns marks an empty
    slot — never matches a real request); zero_bin: [C] int32 (-1 only
    for padded columns no tree names); col: [..., N] int32 requested
    store columns.  Returns [..., N] int32 bin ids.
    """
    hit = cols == col[..., None]                         # [..., N, R]
    bv = jnp.sum(jnp.where(hit, binsv.astype(jnp.int32), 0), axis=-1)
    C = zero_bin.shape[0]
    zb = jnp.maximum(jnp.take(zero_bin, jnp.clip(col, 0, C - 1)), 0)
    return jnp.where(jnp.any(hit, axis=-1), bv, zb)


def _walk_binned_nodes(stack: EnsembleStack, bins_nt,
                       feat_tbl: Optional[jax.Array], meta: EnsembleMeta
                       ) -> jax.Array:
    """The binned ensemble walk itself: parked node per (tree, row) —
    [T, N] int32, leaves encoded as ~leaf, over [N, C] integer bins.
    Shared by the score replay (`predict_ensemble_binned`), the
    leaf-index router (`predict_ensemble_leaf_binned`), and the serving
    request path (`predict_ensemble_quantized`) so the three can never
    disagree on a routing decision — the online refit subsystem depends
    on routing rows to exactly the leaves whose values the replay sums,
    and serving depends on integer compares reproducing the raw f32
    kernel bit-for-bit (lightgbm_tpu/quantize.py).

    bins_nt may instead be the sparse store triple (cols [N, R],
    binsv [N, R], zero_bin [C]) — then every per-level bin gather runs
    `sparse_bin_lookup` over the ELL row segments and the store never
    densifies; the decision logic (`_binned_decide`, the EFB remap) is
    byte-for-byte the same code, so the sparse walk cannot diverge from
    the dense one (tests/test_sparse.py pins the bitwise parity)."""
    sparse = isinstance(bins_nt, (tuple, list))
    if sparse:
        cols, binsv, zero_bin = bins_nt
        cols = cols.astype(jnp.int32)
        zero_bin = zero_bin.astype(jnp.int32)
        N = cols.shape[0]
    else:
        N = bins_nt.shape[0]
        bins_nt = bins_nt.astype(jnp.int32)
    T = stack.nodes.shape[0]
    rows = jnp.arange(N)[None, :]
    node = jnp.broadcast_to(stack.root[:, None], (T, N))
    ft = None if feat_tbl is None else feat_tbl.astype(jnp.int32)

    def bin_at(c):
        if sparse:
            return sparse_bin_lookup(cols, binsv, zero_bin, c)
        return bins_nt[rows, c]

    def step(_, node):
        safe = jnp.maximum(node, 0)
        rec = jnp.take_along_axis(stack.nodes, safe[:, :, None], axis=1)
        f = rec[..., 0].astype(jnp.int32)
        if ft is None:
            bv = bin_at(f)
        else:
            col = ft[0, f]
            off = ft[1, f]
            dflt = ft[2, f]
            ns = ft[3, f]
            pk = ft[4, f] > 0
            bv_store = bin_at(col)
            s = bv_store - off
            in_r = (s >= 0) & (s < ns)
            orig = jnp.where(in_r, s + (s >= dflt).astype(jnp.int32), dflt)
            bv = jnp.where(pk, orig, bv_store)
        gl = _binned_decide(rec, bv, meta.any_cat)
        nxt = jnp.where(gl, rec[..., 3], rec[..., 4]).astype(jnp.int32)
        return jnp.where(node >= 0, nxt, node)

    return jax.lax.fori_loop(0, meta.depth, step, node)


@functools.partial(jax.jit, static_argnames=("meta",))
def predict_ensemble_binned(stack: EnsembleStack, bins_t: jax.Array,
                            feat_tbl: Optional[jax.Array] = None, *,
                            meta: EnsembleMeta) -> jax.Array:
    """Raw per-class scores over the BINNED store — [K, N] f32.

    bins_t: [N+1, C] int store bins (the ScoreUpdater layout — C is
    original features, or bundled columns with `feat_tbl`).  Compares
    stay integer end to end (bin codes vs in-bin thresholds), so replay
    skips float thresholding entirely.  `feat_tbl` ([5, F]: col, offset,
    default, nslots, packed) is the EFB packed-slot remap of
    score_updater._walk_step: trees speak original (feature, bin) space,
    the store speaks bundle space.
    """
    node = _walk_binned_nodes(stack, bins_t[: bins_t.shape[0] - 1],
                              feat_tbl, meta)
    return _leaf_sums(stack, node, meta.num_class)


@functools.partial(jax.jit, static_argnames=("meta",))
def predict_ensemble_binned_sparse(stack: EnsembleStack, cols: jax.Array,
                                   binsv: jax.Array, zero_bin: jax.Array,
                                   feat_tbl: Optional[jax.Array] = None, *,
                                   meta: EnsembleMeta) -> jax.Array:
    """Raw per-class scores over the SPARSE binned store — [K, N] f32,
    without densifying: the score replay for `sparse_store=csr` runs.

    cols/binsv: [N, R] ELL row segments (col >= num_columns = empty
    slot); zero_bin [C] int32.  Per level the walk probes the row's ELL
    segment for the split column (`sparse_bin_lookup`) instead of
    gathering from a dense [N, C] store; the routing decisions are the
    SAME `_walk_binned_nodes` / `_binned_decide` code as the dense
    replay, so scores are bitwise `predict_ensemble_binned` over
    `SparseStore.densify()` on every input.  `feat_tbl` composes: the
    probe answers store-space bins, the EFB remap runs on top.
    """
    node = _walk_binned_nodes(stack, (cols, binsv, zero_bin),
                              feat_tbl, meta)
    return _leaf_sums(stack, node, meta.num_class)


def predict_ensemble_quantized(stack, Xb: jax.Array, *,
                               meta: EnsembleMeta) -> jax.Array:
    """Raw per-class scores over an ingress-quantized request buffer —
    [K, N] f32 from [N, F] uint8/uint16 ORIGINAL per-feature bin ids
    (quantize.FeatureQuantizer) — the binned serving request path.

    Layout dispatch mirrors the raw path: shallow numerical ensembles
    traverse the PERFECT layout (arithmetic navigation; the f32 lanes
    carry bin ids < 2^24, so the compare is exactly the integer
    compare), everything else runs the SoA walk shared with the
    replay/router (`_walk_binned_nodes`) with integer compares end to
    end.  Either way the per-request buffer ships to the device 4x
    smaller than f32, and the quantizer's MISSING sentinel exceeds
    every threshold bin and matches no category bin, so
    NaN/unseen-category rows route exactly like the raw kernel (always
    right); scores are bitwise the raw-feature kernel's on every
    input.  No ``feat_tbl``: trees speak original (feature, bin) space
    and the ingress buffer is built in it — EFB remaps are a
    training-store concern.
    """
    if isinstance(stack, PerfectEnsemble):
        return predict_ensemble_perfect(stack, Xb, meta=meta)
    return _predict_ensemble_quantized_soa(stack, Xb, meta=meta)


@functools.partial(jax.jit, static_argnames=("meta",))
def _predict_ensemble_quantized_soa(stack: EnsembleStack, Xb: jax.Array,
                                    *, meta: EnsembleMeta) -> jax.Array:
    node = _walk_binned_nodes(stack, Xb, None, meta)
    return _leaf_sums(stack, node, meta.num_class)


@functools.partial(jax.jit, static_argnames=("meta",))
def predict_ensemble_leaf_binned(stack: EnsembleStack, bins_t: jax.Array,
                                 feat_tbl: Optional[jax.Array] = None, *,
                                 meta: EnsembleMeta) -> jax.Array:
    """Per-tree leaf index over the BINNED store — [T, N] int32.

    The online-refit router: exactly the walk `predict_ensemble_binned`
    sums values over, returning the parked leaf instead (stumps park at
    leaf 0).  Integer bin compares end to end, so routing is exact on
    any store the trees were rebinned to.
    """
    node = _walk_binned_nodes(stack, bins_t[: bins_t.shape[0] - 1],
                              feat_tbl, meta)
    return jnp.where(node < 0, ~node, 0)


@functools.partial(jax.jit, static_argnames=("meta",))
def predict_ensemble_leaf(stack: EnsembleStack, X: jax.Array, *,
                          meta: EnsembleMeta) -> jax.Array:
    """Per-tree leaf index over RAW feature values — [T, N] int32.

    The tensorized `pred_leaf` kernel: exactly the walk
    `predict_ensemble` sums values over (`_walk_raw_nodes`), returning
    the parked leaf instead — the divergence the walk/tensorized parity
    test pins down cannot reappear while the walk is shared.
    """
    node = _walk_raw_nodes(stack, X.astype(jnp.float32), meta)
    return jnp.where(node < 0, ~node, 0)


# ----------------------------------------------------------------------
# grouped (cross-model) traversal — N co-stacked tenants, ONE launch
# ----------------------------------------------------------------------

def _grouped_sums(stack: EnsembleStack, node: jax.Array,
                  tids: jax.Array, meta: GroupMeta) -> jax.Array:
    """[K, N] per-class sums where row n sums ONLY the trees of its own
    tenant ``tids[n]``.

    The walk above parked every row in every tree (rows do visit
    wrong-tenant trees — those trees gather whichever of the row's
    features their splits name, park somewhere, and are discarded
    here).  Each tenant's reduction is a STATIC slice of the [T, N]
    leaf values (`meta.segments` — trace-time bounds) fed to the SAME
    op and shape `_leaf_sums` uses on the tenant's solo stack: plain
    ``sum(axis=0)`` for K==1, sorted segment-sum over class_id for
    K>1.  Same addends in the same reduction ⇒ bitwise-identical to
    per-tenant dispatch — which is why this is G static slices and NOT
    one masked segment-sum over the concatenated stack (a different
    accumulation order/shape XLA may reassociate differently).
    The final per-row select is a gather over the [G, K, N] stack of
    per-tenant answers; an out-of-range tid clamps (JAX gather
    semantics) rather than reading garbage.
    """
    leaf = jnp.where(node < 0, ~node, 0)
    vals = jnp.take_along_axis(stack.leaf_value, leaf, axis=1)   # [T, N]
    per = []
    for a, b in meta.segments:
        seg = vals[a:b]
        if meta.num_class == 1:
            per.append(jnp.sum(seg, axis=0)[None])
        else:
            per.append(jax.ops.segment_sum(seg, stack.class_id[a:b],
                                           num_segments=meta.num_class,
                                           indices_are_sorted=True))
    sums = jnp.stack(per)                                  # [G, K, N]
    idx = jnp.broadcast_to(tids.astype(jnp.int32)[None, None, :],
                           (1,) + sums.shape[1:])
    return jnp.take_along_axis(sums, idx, axis=0)[0]       # [K, N]


@functools.partial(jax.jit, static_argnames=("meta",))
def predict_ensemble_grouped(stack: EnsembleStack, X: jax.Array,
                             tids: jax.Array, *,
                             meta: GroupMeta) -> jax.Array:
    """Mixed-tenant raw scores over raw features — [K, N] f32.

    One walk of the whole super-stack (every row through every tenant's
    trees — the walk is gather-bound, so surplus trees ride the same
    depth loop), then per-tenant reductions and a per-row tenant
    select.  ``tids``: [N] int — row n's segment index into
    ``meta.segments``.  Bitwise-identical to scoring each row through
    its tenant's solo stack (`_grouped_sums`).
    """
    node = _walk_raw_nodes(stack, X.astype(jnp.float32), meta)
    return _grouped_sums(stack, node, tids, meta)


@functools.partial(jax.jit, static_argnames=("meta",))
def predict_ensemble_grouped_binned(stack: EnsembleStack, Xb: jax.Array,
                                    tids: jax.Array, *,
                                    meta: GroupMeta) -> jax.Array:
    """Mixed-tenant raw scores over ingress-quantized bin ids — [K, N]
    f32 from [N, F] uint8/uint16 ORIGINAL per-feature bin ids.  The
    serving request path under serve_quantize=binned for co-stacked
    tenants: the shared binned walk (`_walk_binned_nodes`, integer
    compares end to end) over the super-stack, then the same per-tenant
    demuxed reduction as the raw grouped kernel.  Every tenant's buffer
    columns must be padded to the group-wide max feature count (the
    group runtime pads; surplus columns are never gathered by that
    tenant's trees, and wrong-tenant trees' gathers are discarded).
    """
    node = _walk_binned_nodes(stack, Xb, None, meta)
    return _grouped_sums(stack, node, tids, meta)


# ----------------------------------------------------------------------
# segment-gathered grouped traversal (costack_kernel=segment) — each
# row walks ONLY its own tenant's tree segment.  The walk-all kernels
# above are gather-bound where launch overhead dominates (the TPU
# premise), but cost ~G x a solo tenant's node math per row on
# compute-bound tiers; here per-depth-level record/feature gathers
# index ``seg_start[tid] + local_tree`` over L = max segment length
# slots, so node math returns to ~1x while the group still compiles
# ONE executable per (bucket, kind).
# ----------------------------------------------------------------------

def _segment_slots(stack: EnsembleStack, tids: jax.Array,
                   meta: GroupMeta) -> tuple:
    """Per-(slot, row) tree indices for the segment-gathered walk:
    ``tree[j, n] = seg_start[tids[n]] + j`` over L = max segment
    length slots, plus the ``valid`` mask (``j < len(segment)``).
    ``meta.segments`` is static, so the offset tables are trace-time
    constants; slots past a short tenant's segment clamp to a real
    tree (walked and discarded — `_segment_sums` zeroes them), and an
    out-of-range tid clamps exactly like `_grouped_sums`' final
    gather."""
    starts = np.fromiter((a for a, _b in meta.segments), np.int32,
                         len(meta.segments))
    stops = np.fromiter((b for _a, b in meta.segments), np.int32,
                        len(meta.segments))
    L = int((stops - starts).max())
    T = stack.nodes.shape[0]
    tids = tids.astype(jnp.int32)
    start = jnp.asarray(starts)[tids]                      # [N]
    length = jnp.asarray(stops - starts)[tids]             # [N]
    j = jnp.arange(L, dtype=jnp.int32)[:, None]            # [L, 1]
    valid = j < length[None, :]                            # [L, N]
    tree = jnp.minimum(start[None, :] + j, T - 1)          # [L, N]
    return tree, valid


def _walk_raw_segment(stack: EnsembleStack, Xf: jax.Array,
                      tree: jax.Array, meta: GroupMeta) -> jax.Array:
    """Raw-feature walk over per-row gathered tree slots: parked node
    per (slot, row) — [L, N] int32, leaves as ~leaf.  Identical
    per-level structure to `_walk_raw_nodes` (one record gather, one
    feature gather, one select) with the tree axis indexed per row
    instead of broadcast; routing decisions go through the SAME
    `_raw_decide`, so a row's own trees park on exactly the leaves the
    walk-all kernel parks them on."""
    rows = jnp.arange(Xf.shape[0])[None, :]

    def step(_, node):
        safe = jnp.maximum(node, 0)
        rec = stack.nodes[tree, safe]                      # [L, N, lanes]
        f = rec[..., 0].astype(jnp.int32)
        v = Xf[rows, f]                                    # [L, N]
        gl = _raw_decide(rec, v, meta.any_cat)
        nxt = jnp.where(gl, rec[..., 3], rec[..., 4]).astype(jnp.int32)
        return jnp.where(node >= 0, nxt, node)

    return jax.lax.fori_loop(0, meta.depth, step, stack.root[tree])


def _walk_binned_segment(stack: EnsembleStack, bins_nt: jax.Array,
                         tree: jax.Array, meta: GroupMeta) -> jax.Array:
    """Binned walk over per-row gathered tree slots — `_walk_raw_segment`
    with integer compares through the shared `_binned_decide` (the
    serving request path under serve_quantize=binned; no ``feat_tbl``:
    request buffers speak original (feature, bin) space)."""
    bins_nt = bins_nt.astype(jnp.int32)
    rows = jnp.arange(bins_nt.shape[0])[None, :]

    def step(_, node):
        safe = jnp.maximum(node, 0)
        rec = stack.nodes[tree, safe]                      # [L, N, lanes]
        f = rec[..., 0].astype(jnp.int32)
        bv = bins_nt[rows, f]                              # [L, N]
        gl = _binned_decide(rec, bv, meta.any_cat)
        nxt = jnp.where(gl, rec[..., 3], rec[..., 4]).astype(jnp.int32)
        return jnp.where(node >= 0, nxt, node)

    return jax.lax.fori_loop(0, meta.depth, step, stack.root[tree])


def _segment_sums(stack: EnsembleStack, node: jax.Array, tree: jax.Array,
                  valid: jax.Array, meta: GroupMeta) -> jax.Array:
    """[K, N] per-class sums of the [L, N] segment walk's parked leaf
    values — the demux of the segment kernels.

    Row n's slots hold ITS tenant's trees in stack order (class-major —
    exactly the solo stack order), padded slots gather a clamped tree
    and mask to an exact +0.0 addend.  The reduction therefore adds the
    same fp32 dyadic leaf values in the same order as the solo
    reduction (`_leaf_sums`) with exact-zero padding interleaved —
    exact for the dyadic leaf-value domain every grouped/solo parity
    in this module already stands on, and pinned bitwise against both
    `_grouped_sums` and per-tenant dispatch in tests/test_costack.py.
    K>1 demuxes by each slot's gathered class id (sorted within a
    segment, so each class's trees still add in stack order) with a
    sequential in-slot-order accumulation: `jax.ops.segment_sum` — the
    solo/`_grouped_sums` K>1 reduction — adds segment members
    sequentially in index order, and a masked `jnp.sum` over the slot
    axis reassociates (pairwise) and lands ~1 ulp off, so the loop is
    what keeps the multiclass demux bitwise."""
    leaf = jnp.where(node < 0, ~node, 0)
    vals = jnp.where(valid, stack.leaf_value[tree, leaf],
                     jnp.float32(0.0))                     # [L, N]
    if meta.num_class == 1:
        return jnp.sum(vals, axis=0)[None]
    cls = stack.class_id[tree]                             # [L, N]
    ks = jnp.arange(meta.num_class, dtype=cls.dtype)[:, None]

    def step(j, acc):
        return acc + jnp.where(cls[j][None, :] == ks, vals[j][None, :],
                               jnp.float32(0.0))

    return jax.lax.fori_loop(0, vals.shape[0], step,
                             jnp.zeros((meta.num_class, node.shape[1]),
                                       jnp.float32))


@functools.partial(jax.jit, static_argnames=("meta",))
def predict_ensemble_grouped_segment(stack: EnsembleStack, X: jax.Array,
                                     tids: jax.Array, *,
                                     meta: GroupMeta) -> jax.Array:
    """Mixed-tenant raw scores over raw features, segment-gathered —
    [K, N] f32, bitwise-identical to `predict_ensemble_grouped` and to
    per-tenant dispatch.  Row n walks the L = max-segment-length tree
    slots of its own tenant instead of all T_total stacked trees: same
    ONE launch per (bucket, kind), per-row node math back to ~1x."""
    tree, valid = _segment_slots(stack, tids, meta)
    node = _walk_raw_segment(stack, X.astype(jnp.float32), tree, meta)
    return _segment_sums(stack, node, tree, valid, meta)


@functools.partial(jax.jit, static_argnames=("meta",))
def predict_ensemble_grouped_segment_binned(stack: EnsembleStack,
                                            Xb: jax.Array,
                                            tids: jax.Array, *,
                                            meta: GroupMeta) -> jax.Array:
    """Mixed-tenant raw scores over ingress-quantized bin ids,
    segment-gathered — the binned twin of
    `predict_ensemble_grouped_segment` (integer compares end to end;
    buffers padded to the group-wide max feature count exactly like
    `predict_ensemble_grouped_binned`)."""
    tree, valid = _segment_slots(stack, tids, meta)
    node = _walk_binned_segment(stack, Xb, tree, meta)
    return _segment_sums(stack, node, tree, valid, meta)
