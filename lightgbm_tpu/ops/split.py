"""Vectorized best-split search over histograms.

Replaces the reference's per-feature sequential scans
(FeatureHistogram::FindBestThresholdNumerical/Categorical,
/root/reference/src/treelearner/feature_histogram.hpp:75-249) with one
cumulative-sum scan over ALL features' bins at once — `[F, B]` arrays on
the VPU instead of an OMP loop of scalar scans.

Math parity (feature_histogram.hpp:281-300):
  gain(G, H)   = max(0, |G| - l1)^2 / (H + l2)
  leaf_out(G,H)= -copysign(max(0, |G| - l1), G) / (H + l2)
  split gain reported = gain(GL,HL) + gain(GR,HR) - gain(G,H)
  valid iff both children satisfy min_data_in_leaf / min_sum_hessian_in_leaf
  and the total gain exceeds gain(G,H) + min_gain_to_split.

Numerical thresholds: rows with bin <= t go left (tree.h NumericalDecision).
Categorical: one-vs-rest, rows with bin == t go left (threshold is the bin).

Tie-break: flat argmax over [F, B] picks the smallest feature id then the
smallest threshold — matching the reference's deterministic tie-break
(split_info.hpp:100-105; its right-to-left scan with strict `>` also keeps
the smallest threshold).
"""
from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

K_MIN_SCORE = -jnp.inf
K_EPSILON = 1e-15  # reference meta.h kEpsilon


class SplitResult(NamedTuple):
    """Device split record (all [*] scalars).  `packed()` flattens to one
    f32 vector so the host fetches a single small transfer per split."""
    gain: jax.Array
    feature: jax.Array        # inner (used-feature) index, int32
    threshold_bin: jax.Array  # int32
    left_sum_grad: jax.Array
    left_sum_hess: jax.Array
    left_count: jax.Array
    right_sum_grad: jax.Array
    right_sum_hess: jax.Array
    right_count: jax.Array
    left_output: jax.Array
    right_output: jax.Array

    def packed(self) -> jax.Array:
        return jnp.stack([self.gain, self.feature.astype(jnp.float32),
                          self.threshold_bin.astype(jnp.float32),
                          self.left_sum_grad, self.left_sum_hess,
                          self.left_count, self.right_sum_grad,
                          self.right_sum_hess, self.right_count,
                          self.left_output, self.right_output])


# ----------------------------------------------------------------------------
# Exclusive Feature Bundling support (binning.BundlePlan device side)
# ----------------------------------------------------------------------------

def identity_feat_table(num_bins) -> "jnp.ndarray":
    """[5, F] feat table for an UNBUNDLED store: every feature is its own
    column, packed=0, so bundle_predicate_params degenerates to the plain
    (feature, threshold) predicate.  Accepts host or traced num_bins."""
    F = num_bins.shape[0] if hasattr(num_bins, "shape") else len(num_bins)
    z = jnp.zeros(F, jnp.float32)
    return jnp.stack([jnp.arange(F, dtype=jnp.float32), z, z,
                      jnp.asarray(num_bins).astype(jnp.float32), z])


def bundle_predicate_params(feat_tbl, feat, thr, is_cat):
    """Translate an ORIGINAL-space split (feature, threshold bin, is-cat)
    into STORE-space go-left parameters (col, T, lo, hi1, dl):

        in_range = lo <= store_bin <= hi1
        go_left  = in_range ? (is_cat ? store_bin == T : store_bin <= T)
                            : dl

    feat_tbl: [5, F] f32 rows (col, offset, default, nslots, packed) —
    binning.BundlePlan.feat_table() or identity_feat_table().  Works for
    scalar or vector `feat`/`thr`/`is_cat` (all traced).

    Slot packing keeps bin order with the default bin removed, so a
    numerical `orig_bin <= thr` is exactly the slot interval
    [offset, offset + thr - (thr >= default)]; rows outside the feature's
    slot range sit at the default bin, which goes left iff default <= thr
    (numerical) / default == thr (categorical).  For a categorical split
    ON the default bin, T = offset - 1 matches no in-range slot (offsets
    start at 1) and dl sends the default rows left."""
    feat = jnp.asarray(feat, jnp.int32)
    thr = jnp.asarray(thr, jnp.int32)
    feat_tbl = jnp.asarray(feat_tbl)   # may arrive as a host constant
    col = feat_tbl[0, feat].astype(jnp.int32)
    off = feat_tbl[1, feat].astype(jnp.int32)
    d = feat_tbl[2, feat].astype(jnp.int32)
    ns = feat_tbl[3, feat].astype(jnp.int32)
    pk = feat_tbl[4, feat] > 0
    t_num = off + thr - (thr >= d).astype(jnp.int32)
    t_cat = jnp.where(thr == d, off - 1,
                      off + thr - (thr > d).astype(jnp.int32))
    T = jnp.where(pk, jnp.where(is_cat, t_cat, t_num), thr)
    lo = jnp.where(pk, off, 0)
    hi1 = jnp.where(pk, off + ns - 1, jnp.int32(1 << 30))
    dl = pk & jnp.where(is_cat, thr == d, d <= thr)
    return col, T, lo, hi1, dl


def store_go_left(store_bin, T, lo, hi1, dl, is_cat):
    """Evaluate the store-space predicate of bundle_predicate_params on a
    row vector of store bins."""
    in_r = (store_bin >= lo) & (store_bin <= hi1)
    gl = jnp.where(is_cat, store_bin == T, store_bin <= T)
    return jnp.where(in_r, gl, dl)


def unbundle_hist(hist: jax.Array, src: jax.Array, dmask: jax.Array,
                  totals: jax.Array) -> jax.Array:
    """Bundled histogram [C, 3, B] -> original-feature histogram [F, 3, B].

    src/dmask come from binning.BundlePlan.unbundle_tables: `src[f, b]`
    is a flat index into the [C*B] store histogram (C*B = zero sentinel
    for out-of-range bins and the default slot), and `dmask` marks each
    packed feature's default bin, reconstructed as
    `leaf_totals - sum(non-default bins)` — exact under zero conflicts
    (every row of the leaf lands in exactly one bin of each feature; the
    reference reconstructs sparse-bin zero entries the same way).
    `totals` is the leaf's [3] (sum_grad, sum_hess, count)."""
    C, _, B = hist.shape
    flat = hist.transpose(0, 2, 1).reshape(C * B, 3)
    flat = jnp.concatenate([flat, jnp.zeros((1, 3), flat.dtype)], axis=0)
    F, Bo = src.shape
    g = flat[src.reshape(-1)].reshape(F, Bo, 3).transpose(0, 2, 1)
    fill = totals[None, :, None] - jnp.sum(g, axis=2, keepdims=True)
    return jnp.where(dmask[:, None, :], fill, g)


def maybe_unbundle(hist: jax.Array, unb, totals: jax.Array) -> jax.Array:
    """unb is None (store is the original layout) or (src, dmask)."""
    if unb is None:
        return hist
    return unbundle_hist(hist, unb[0], unb[1], totals)


def unbundle_hist_local(hist: jax.Array, src: jax.Array, dmask: jax.Array,
                        totals: jax.Array, col_start) -> tuple:
    """Per-shard unbundle for the psum_scatter exchange: `hist` is a
    store-column SLICE [Cs, 3, B] holding global columns
    [col_start, col_start + Cs) of a reduce-scattered histogram;
    src/dmask are the GLOBAL tables of BundlePlan.unbundle_tables
    (flat indices into [C*B], sentinel C*B with C the padded column
    count — the store must be padded so the shard slices tile C exactly
    and the sentinel stays outside every slice's range).

    Returns ([F, 3, B] histogram, owned [F] bool).  An original feature
    lives entirely in ONE store column, so it is exact on the shard
    owning that column and garbage elsewhere (its default-bin fill
    reconstructs from zero sums); the split search must AND `owned`
    into its feature mask so only the owning shard's record for each
    feature survives the cross-shard argmax."""
    Cs, _, B = hist.shape
    src = jnp.asarray(src)
    col_start = jnp.asarray(col_start, jnp.int32)
    lo = col_start * B
    # the global sentinel C*B sits past the last shard's range, so
    # in_range is False for every invalid-bin entry on every shard
    in_range = (src >= lo) & (src < lo + Cs * B)
    owned = jnp.any(in_range, axis=1)
    src_l = jnp.where(in_range, src - lo, Cs * B)
    flat = hist.transpose(0, 2, 1).reshape(Cs * B, 3)
    flat = jnp.concatenate([flat, jnp.zeros((1, 3), flat.dtype)], axis=0)
    F, Bo = src_l.shape
    g = flat[src_l.reshape(-1)].reshape(F, Bo, 3).transpose(0, 2, 1)
    fill = totals[None, :, None] - jnp.sum(g, axis=2, keepdims=True)
    return jnp.where(jnp.asarray(dmask)[:, None, :], fill, g), owned


def sharded_slice_search(h, sums, *, off, nb_s, ic_s, fm_s,
                         num_bins, is_cat, fmask, unb, skw) -> jax.Array:
    """Per-shard best split of ONE leaf from its reduce-scattered
    store-column slice (the psum_scatter exchange of learner/rounds.py
    and learner/fused.py — shared so the two learners cannot diverge).

    h : [Cs, 3, B] this shard's reduced column slice; off: the shard's
    first global column.  Identity store (unb None): nb_s/ic_s/fm_s are
    the shard's dynamic metadata slices and the record's feature id gets
    `off` folded back in.  Bundled store: the slice is unbundled to the
    full original-feature layout with non-owned features masked out of
    the search.  Returns the packed [11] record in ORIGINAL feature
    space; combine across shards with `combine_sharded_records`."""
    if unb is None:
        rec = best_split(h, nb_s, ic_s, fm_s,
                         sums[0], sums[1], sums[2], **skw)
        p = rec.packed()
        return p.at[1].add(jnp.asarray(off).astype(jnp.float32))
    hF, owned = unbundle_hist_local(h, unb[0], unb[1], sums, off)
    rec = best_split(hF, num_bins, is_cat, fmask & owned,
                     sums[0], sums[1], sums[2], **skw)
    return rec.packed()


def combine_sharded_records(recs: jax.Array, axis_name) -> jax.Array:
    """all_gather the per-shard packed records over `axis_name` and pick
    each leaf's winner: maximum gain, ties broken by the SMALLEST
    feature id — every feature is owned by exactly one shard, so this
    reproduces the full search's flat-argmax tie-break exactly even
    when feature→shard ownership is not monotone in feature id (EFB
    bundles order shards by store column, not original feature).

    recs: [..., 11] (a single record or a [K, 11] batch); returns the
    same shape, replicated across the axis.

    REPLICATION CONTRACT: every shard receives the identical winning
    record (all_gather is replicated and the argmin over it is
    deterministic), so results may legally gate replicated control
    flow.  shardlint's taint lattice (diagnostics/lint.py) encodes this
    by name — treat this function like psum when reasoning about
    divergence — and the DivergenceSanitizer checksums the downstream
    tree state at run time."""
    allr = jax.lax.all_gather(recs, axis_name)       # [nd, ..., 11]
    gains = allr[..., 0]
    mx = jnp.max(gains, axis=0, keepdims=True)
    cand = jnp.where(gains == mx, allr[..., 1], jnp.inf)
    best = jnp.argmin(cand, axis=0)
    return jnp.take_along_axis(allr, best[None, ..., None],
                               axis=0).squeeze(0)


def leaf_split_gain(G, H, l1, l2):
    reg = jnp.maximum(jnp.abs(G) - l1, 0.0)
    return reg * reg / (H + l2)


def leaf_output(G, H, l1, l2):
    reg = jnp.maximum(jnp.abs(G) - l1, 0.0)
    return -jnp.sign(G) * reg / (H + l2)


def split_gain_matrix(hist: jax.Array, num_bins: jax.Array, is_cat: jax.Array,
                      feature_mask: jax.Array, sum_grad: jax.Array,
                      sum_hess: jax.Array, num_data: jax.Array, *,
                      lambda_l1: float = 0.0, lambda_l2: float = 0.0,
                      min_data_in_leaf: int = 20,
                      min_sum_hessian_in_leaf: float = 1e-3,
                      min_gain_to_split: float = 0.0):
    """[F, B] total gain per candidate threshold (K_MIN_SCORE where
    invalid), plus (GL, HL, CL) cumulatives for record assembly.  Exposed
    separately from `best_split` so the voting-parallel learner can rank
    features locally (voting_parallel_tree_learner.cpp local top-k)."""
    F, _, B = hist.shape
    l1, l2 = lambda_l1, lambda_l2
    g, h, c = hist[:, 0, :], hist[:, 1, :], hist[:, 2, :]

    bin_idx = jax.lax.broadcasted_iota(jnp.int32, (F, B), 1)
    nb = num_bins[:, None]

    # ---- numerical: left = bins <= t, valid t in [0, nb-2] ----------------
    GL = jnp.cumsum(g, axis=1)
    HL = jnp.cumsum(h, axis=1)
    CL = jnp.cumsum(c, axis=1)
    # ---- categorical: left = bin == t, valid t in [0, nb-1] ---------------
    GL = jnp.where(is_cat[:, None], g, GL)
    HL = jnp.where(is_cat[:, None], h, HL)
    CL = jnp.where(is_cat[:, None], c, CL)

    GR = sum_grad - GL
    HR = sum_hess - HL
    CR = num_data - CL

    t_valid = jnp.where(is_cat[:, None], bin_idx < nb, bin_idx < nb - 1)
    valid = (t_valid & feature_mask[:, None]
             & (CL >= min_data_in_leaf) & (CR >= min_data_in_leaf)
             & (HL >= min_sum_hessian_in_leaf)
             & (HR >= min_sum_hessian_in_leaf))

    gain_shift = leaf_split_gain(sum_grad, sum_hess, l1, l2)
    min_gain_shift = gain_shift + min_gain_to_split
    total_gain = leaf_split_gain(GL, HL, l1, l2) + leaf_split_gain(GR, HR, l1, l2)
    total_gain = jnp.where(valid & (total_gain > min_gain_shift),
                           total_gain, K_MIN_SCORE)
    return total_gain, GL, HL, CL


@functools.partial(
    jax.jit,
    static_argnames=("lambda_l1", "lambda_l2", "min_data_in_leaf",
                     "min_sum_hessian_in_leaf", "min_gain_to_split"))
def best_split(hist: jax.Array, num_bins: jax.Array, is_cat: jax.Array,
               feature_mask: jax.Array, sum_grad: jax.Array,
               sum_hess: jax.Array, num_data: jax.Array, *,
               lambda_l1: float = 0.0, lambda_l2: float = 0.0,
               min_data_in_leaf: int = 20,
               min_sum_hessian_in_leaf: float = 1e-3,
               min_gain_to_split: float = 0.0) -> SplitResult:
    """Find the best split of one leaf from its histogram.

    hist : [F, 3, B] f32 (sum_grad, sum_hess, count)
    num_bins : [F] int32 actual bins per feature
    is_cat : [F] bool
    feature_mask : [F] bool (feature_fraction subset for this tree)
    sum_grad/sum_hess/num_data : leaf totals (host-accurate scalars)
    """
    F, _, B = hist.shape
    l1, l2 = lambda_l1, lambda_l2
    total_gain, GL, HL, CL = split_gain_matrix(
        hist, num_bins, is_cat, feature_mask, sum_grad, sum_hess, num_data,
        lambda_l1=lambda_l1, lambda_l2=lambda_l2,
        min_data_in_leaf=min_data_in_leaf,
        min_sum_hessian_in_leaf=min_sum_hessian_in_leaf,
        min_gain_to_split=min_gain_to_split)
    gain_shift = leaf_split_gain(sum_grad, sum_hess, l1, l2)

    flat = total_gain.reshape(-1)
    best = jnp.argmax(flat)
    bf = (best // B).astype(jnp.int32)
    bt = (best % B).astype(jnp.int32)
    bg = flat[best]
    glb, hlb, clb = GL.reshape(-1)[best], HL.reshape(-1)[best], CL.reshape(-1)[best]
    grb, hrb, crb = sum_grad - glb, sum_hess - hlb, num_data - clb
    return SplitResult(
        gain=jnp.where(jnp.isfinite(bg), bg - gain_shift, K_MIN_SCORE),
        feature=bf, threshold_bin=bt,
        left_sum_grad=glb, left_sum_hess=hlb, left_count=clb,
        right_sum_grad=grb, right_sum_hess=hrb, right_count=crb,
        left_output=leaf_output(glb, hlb, l1, l2),
        right_output=leaf_output(grb, hrb, l1, l2))
