"""Device-side evaluation metric kernels.

The reference evaluates metrics on the host over the full score vector
(/root/reference/src/metric/*.hpp, driven per-iteration from
gbdt.cpp:520-578).  On TPU that design forces a [K, N] device→host fetch
plus a host pass every eval round — at HIGGS scale (10.5M rows) the fetch
alone is ~40 MB and a host AUC sort costs seconds.  These kernels keep the
score resident and return scalars instead: one float crosses the boundary
per metric.

Every kernel is jitted with static weighted/unweighted variants so the
unweighted common case never materializes a ones vector.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


# ---------------------------------------------------------------------------
# generic weighted averaging
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnames=("kind",))
def pointwise_loss(score, label, w, sum_w, *, kind: str,
                   p1: float = 0.0, p2: float = 0.0):
    """Weighted mean of an elementwise loss.  score/label [N] f32,
    w [N] or None, sum_w scalar.  `kind` selects the loss; p1/p2 are the
    loss parameters (sigmoid / huber delta / fair c...)."""
    s = score.astype(jnp.float32)
    y = label
    if kind == "l2":
        d = s - y
        loss = d * d
    elif kind == "l1":
        loss = jnp.abs(s - y)
    elif kind == "huber":
        d = jnp.abs(s - y)
        loss = jnp.where(d <= p1, 0.5 * d * d, p1 * (d - 0.5 * p1))
    elif kind == "fair":
        x = jnp.abs(s - y)
        loss = p1 * x - p1 * p1 * jnp.log1p(x / p1)
    elif kind == "poisson":
        sv = jnp.maximum(s, 1e-10)
        loss = sv - y * jnp.log(sv)
    elif kind == "binary_logloss":
        prob = jax.nn.sigmoid(p1 * s)
        prob = jnp.clip(prob, 1e-15, 1 - 1e-15)
        loss = -jnp.where(y > 0, jnp.log(prob), jnp.log1p(-prob))
    elif kind == "binary_error":
        loss = ((s > 0) != (y > 0)).astype(jnp.float32)
    else:  # pragma: no cover
        raise ValueError(kind)
    if w is None:
        return jnp.sum(loss) / sum_w
    return jnp.sum(loss * w) / sum_w


@jax.jit
def auc(score, label, w):
    """Weighted tie-aware rank-sum AUC (binary_metric.hpp:156+), fully on
    device: sort once, fold tied blocks with a segment-sum keyed by a
    block id derived from score changes."""
    s = score.astype(jnp.float32)
    n = s.shape[0]
    order = jnp.argsort(s, stable=True)
    s_s = s[order]
    y_s = label[order] > 0
    w_s = jnp.ones_like(s) if w is None else w[order]
    wpos = jnp.where(y_s, w_s, 0.0)
    wneg = jnp.where(y_s, 0.0, w_s)
    new_block = jnp.concatenate(
        [jnp.ones(1, jnp.int32), (s_s[1:] != s_s[:-1]).astype(jnp.int32)])
    block_id = jnp.cumsum(new_block) - 1                       # [N]
    bpos = jax.ops.segment_sum(wpos, block_id, num_segments=n)
    bneg = jax.ops.segment_sum(wneg, block_id, num_segments=n)
    below = jnp.cumsum(bneg) - bneg          # negatives strictly below block
    acc = jnp.sum(bpos * (below + 0.5 * bneg))
    tot_pos = jnp.sum(wpos)
    tot_neg = jnp.sum(wneg)
    return jnp.where((tot_pos > 0) & (tot_neg > 0),
                     acc / (tot_pos * tot_neg), 1.0)


@jax.jit
def multi_logloss(score, label_int, w, sum_w):
    """score [K, N], label_int [N] int32."""
    s = score.astype(jnp.float32)
    m = jnp.max(s, axis=0, keepdims=True)
    logp = s - m - jnp.log(jnp.sum(jnp.exp(s - m), axis=0, keepdims=True))
    pl = jnp.take_along_axis(logp, label_int[None, :], axis=0)[0]
    loss = -jnp.maximum(pl, jnp.log(1e-15))
    if w is None:
        return jnp.sum(loss) / sum_w
    return jnp.sum(loss * w) / sum_w


@jax.jit
def multi_error(score, label_int, w, sum_w):
    pred = jnp.argmax(score, axis=0).astype(jnp.int32)
    err = (pred != label_int).astype(jnp.float32)
    if w is None:
        return jnp.sum(err) / sum_w
    return jnp.sum(err * w) / sum_w


# ---------------------------------------------------------------------------
# ranking metrics — vectorized over all queries at once
# ---------------------------------------------------------------------------


def _qw_mean(per_query, query_weight):
    """Query-weighted average of a [Q] per-query metric vector; uniform
    mean when query_weight is None (the traced signature differs, so
    each case compiles its own specialization)."""
    if query_weight is None:
        return jnp.mean(per_query)
    w = query_weight.astype(jnp.float32)
    return jnp.sum(per_query * w) / jnp.sum(w)
# The reference walks queries one by one (rank_metric.hpp, map_metric.hpp);
# at MS-LTR scale (~31k queries) a per-query host loop dominates training.
# Here the per-query sort becomes ONE lexicographic sort of all rows keyed
# (query_id, -score) and the per-query truncated sums become segment-sums.

@functools.partial(jax.jit, static_argnames=("ks", "num_queries"))
def ndcg_at_k(score, label_int, query_id, query_start_of_row, label_gain,
              discount_by_rank, query_weight=None, *, ks: tuple,
              num_queries: int):
    """NDCG@k for every k in `ks`, averaged over queries.

    query_id            [N] int32 — query of each row
    query_start_of_row  [N] int32 — first row index of that query
    label_gain          [G] f32   — gain table
    discount_by_rank    [N] f32   — 1/log2(2+rank) precomputed to max length
    query_weight        [Q] f32 or None — per-query weights for the average
                        (rank_metric.hpp:113-142 weighted branch)
    Returns [len(ks)] f32.
    """
    s = score.astype(jnp.float32)
    n = s.shape[0]
    gains = label_gain[label_int]
    # one global sort: by query, then score desc, stable
    order = jnp.lexsort((-s, query_id))
    rank = jnp.arange(n, dtype=jnp.int32) - query_start_of_row[order]
    g_sorted = gains[order]
    qid_sorted = query_id[order]
    # ideal ordering: by query, then label desc
    iorder = jnp.lexsort((-gains, query_id))
    ig_sorted = gains[iorder]
    out = []
    for k in ks:
        within = rank < k
        disc = discount_by_rank[jnp.minimum(rank, n - 1)]
        dcg = jax.ops.segment_sum(
            jnp.where(within, g_sorted * disc, 0.0), qid_sorted,
            num_segments=num_queries)
        maxdcg = jax.ops.segment_sum(
            jnp.where(within, ig_sorted * disc, 0.0), qid_sorted,
            num_segments=num_queries)
        # all-zero-gain queries count as 1 (rank_metric.hpp convention)
        nd = jnp.where(maxdcg > 0, dcg / jnp.maximum(maxdcg, 1e-30), 1.0)
        out.append(_qw_mean(nd, query_weight))
    return jnp.stack(out)


@functools.partial(jax.jit, static_argnames=("ks", "num_queries"))
def map_at_k(score, label_pos, query_id, query_start_of_row,
             query_weight=None, *, ks: tuple, num_queries: int):
    """MAP@k (map_metric.hpp semantics as implemented by the host metric:
    AP@k = sum_{i<k, rel_i} prec@i / #rel@k, queries with no relevant doc
    in the top k are skipped from the average; query_weight [Q] weights
    the per-query average, map_metric.hpp:113-133)."""
    s = score.astype(jnp.float32)
    n = s.shape[0]
    rel = label_pos.astype(jnp.float32)
    order = jnp.lexsort((-s, query_id))
    rank = jnp.arange(n, dtype=jnp.int32) - query_start_of_row[order]
    rel_sorted = rel[order]
    qid_sorted = query_id[order]
    # hits within query = global cumsum minus the query-start offset
    csum = jnp.cumsum(rel_sorted)
    offset = csum - rel_sorted  # hits strictly before this row, global
    # per-query: hits before query start
    first_offset = jax.ops.segment_min(offset, qid_sorted,
                                       num_segments=num_queries)
    hits = offset - first_offset[qid_sorted] + rel_sorted
    prec = hits / (1.0 + rank.astype(jnp.float32))
    out = []
    for k in ks:
        within = rank < k
        ap_num = jax.ops.segment_sum(
            jnp.where(within, prec * rel_sorted, 0.0), qid_sorted,
            num_segments=num_queries)
        nrel = jax.ops.segment_sum(
            jnp.where(within, rel_sorted, 0.0), qid_sorted,
            num_segments=num_queries)
        ap = jnp.where(nrel > 0, ap_num / jnp.maximum(nrel, 1.0), 0.0)
        out.append(_qw_mean(ap, query_weight))
    return jnp.stack(out)
