"""Histogram construction — the hottest op in the framework.

Replaces the reference's scalar CPU kernels (dense_bin.hpp:67-120) and the
OpenCL local-memory-atomic kernels (ocl/histogram{16,64,256}.cl) with a
TPU-idiomatic formulation: bins are one-hot encoded on the fly and reduced
with a matmul so the accumulation runs on the MXU — there are no fast
device atomics on TPU, but `one_hot(bins).T @ [grad, hess, 1]` is exactly a
`[B, C] @ [C, 3]` contraction (SURVEY.md §7 "hard parts").

Canonical output layout: `[F, 3, B]` float32 — (sum_grad, sum_hess, count)
per feature per bin; B is the padded per-feature bin count.  Accumulation
is fp32 (the reference GPU learner also uses single precision by default,
gpu_tree_learner.h:79-83, and reports accuracy parity).

Two implementations:
- `hist_xla`: chunked one-hot einsum, pure XLA.  Used on CPU (tests) and as
  the fallback.
- `hist_pallas`: Pallas TPU kernel; grid over (feature, row-chunk), one-hot
  built in VMEM and contracted immediately, fp32 accumulate in the output
  block across row-chunks.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np


def _pick_chunk(F: int, B: int, target_bytes: int = 1 << 26) -> int:
    """Row-chunk size so the transient one-hot stays ~64MB."""
    per_row = max(F * B * 2, 1)
    c = max(256, target_bytes // per_row)
    return int(2 ** int(np.floor(np.log2(c))))


@functools.partial(jax.jit, static_argnames=("num_bins_padded", "input_dtype"))
def hist_xla(gb: jax.Array, vals: jax.Array, *, num_bins_padded: int,
             input_dtype: str = "float32") -> jax.Array:
    """Chunked one-hot matmul histogram.

    Parameters
    ----------
    gb : [C, F] integer bin ids of the gathered rows (sentinel rows have
         arbitrary bins but zero vals).
    vals : [3, C] float32 rows (grad, hess, count-mask).
    Returns [F, 3, B] float32.
    """
    C, F = gb.shape
    B = num_bins_padded
    dt = jnp.dtype(input_dtype)
    chunk = min(_pick_chunk(F, B), C)
    n_chunks = max(C // chunk, 1)
    rem = C - n_chunks * chunk

    prec = (jax.lax.Precision.HIGHEST if dt == jnp.float32
            else jax.lax.Precision.DEFAULT)

    def body(acc, args):
        gbc, vc = args  # [chunk, F], [3, chunk]
        oh = (gbc[:, :, None] == jax.lax.broadcasted_iota(
            gbc.dtype, (1, 1, B), 2)).astype(dt)
        acc = acc + jnp.einsum(
            "sc,cfb->fsb", vc.astype(dt), oh,
            preferred_element_type=jnp.float32, precision=prec)
        return acc, None

    acc0 = jnp.zeros((F, 3, B), jnp.float32)
    main = (gb[: n_chunks * chunk].reshape(n_chunks, chunk, F),
            vals[:, : n_chunks * chunk].reshape(3, n_chunks, chunk)
            .transpose(1, 0, 2))
    acc, _ = jax.lax.scan(body, acc0, main)
    if rem:
        acc, _ = body(acc, (gb[n_chunks * chunk:], vals[:, n_chunks * chunk:]))
    return acc


# ----------------------------------------------------------------------------
# Pallas TPU kernel
# ----------------------------------------------------------------------------

FEATURE_GROUP = 8  # features per kernel block (TPU second-minor tiling)


def _hist_kernel(gb_ref, vals_ref, out_ref, *, B: int, input_dtype):
    """One (feature-group, row-chunk) grid cell.

    gb_ref: [1, G, Ck] int32 bins of G features for this row chunk
    vals_ref: [8, Ck] float32 (grad, hess, mask, 5 pad rows)
    out_ref: [1, G, 8, B] float32 accumulated across the chunk grid axis

    TPU block shapes need the last two dims (8|16|32, 128)-aligned
    (pallas guide "tiling"): grouping G=8 features per block keeps every
    ref legal, and the G one-hot matmuls unroll inside the kernel.
    """
    from jax.experimental import pallas as pl

    k = pl.program_id(1)

    @pl.when(k == 0)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    vals = vals_ref[:].astype(input_dtype)      # [8, Ck]
    # f32 inputs get full-precision (3-pass) MXU matmuls; bf16 runs fast
    prec = (jax.lax.Precision.HIGHEST if input_dtype == jnp.float32
            else jax.lax.Precision.DEFAULT)
    G = gb_ref.shape[1]
    for g in range(G):
        gb = gb_ref[0, g, :]                    # [Ck]
        oh = (gb[:, None] == jax.lax.broadcasted_iota(
            jnp.int32, (1, B), 1)).astype(input_dtype)   # [Ck, B]
        out_ref[0, g, :, :] += jnp.dot(
            vals, oh, preferred_element_type=jnp.float32, precision=prec)


@functools.partial(jax.jit, static_argnames=("num_bins_padded", "input_dtype"))
def hist_pallas(gb_t: jax.Array, vals8: jax.Array, *, num_bins_padded: int,
                input_dtype: str = "bfloat16") -> jax.Array:
    """Pallas histogram.  gb_t: [F, C] int32, vals8: [8, C] float32.

    Returns [F, 3, B] float32.
    """
    from jax.experimental import pallas as pl

    F, C = gb_t.shape
    B = num_bins_padded
    G = FEATURE_GROUP
    Ck = min(C, 2048)
    if C % Ck:
        # pad rows to a chunk multiple; padded slots have zero vals so they
        # contribute nothing to any bin
        pad = Ck - C % Ck
        gb_t = jnp.pad(gb_t, ((0, 0), (0, pad)))
        vals8 = jnp.pad(vals8, ((0, 0), (0, pad)))
        C += pad
    Fg = G * ((F + G - 1) // G)
    if Fg > F:
        gb_t = jnp.pad(gb_t, ((0, Fg - F), (0, 0)))
    gb_g = gb_t.reshape(Fg // G, G, C)
    grid = (Fg // G, C // Ck)
    dt = jnp.dtype(input_dtype)

    out = pl.pallas_call(
        functools.partial(_hist_kernel, B=B, input_dtype=dt),
        out_shape=jax.ShapeDtypeStruct((Fg // G, G, 8, B), jnp.float32),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, G, Ck), lambda f, k: (f, 0, k)),
            pl.BlockSpec((8, Ck), lambda f, k: (0, k)),
        ],
        out_specs=pl.BlockSpec((1, G, 8, B), lambda f, k: (f, 0, 0, 0)),
    )(gb_g, vals8)
    return out.reshape(Fg, 8, B)[:F, :3, :]


# ----------------------------------------------------------------------------
# Public entry: gather + histogram
# ----------------------------------------------------------------------------

def histogram_from_indices(bins_t: jax.Array, grad_pad: jax.Array,
                           hess_pad: jax.Array, idx: jax.Array, *,
                           num_bins_padded: int, backend: str = "xla",
                           input_dtype: str = "float32") -> jax.Array:
    """hist [F, 3, B] over the rows named by `idx`.

    bins_t : [N+1, F] integer bins, row N is the sentinel (any value).
    grad_pad, hess_pad : [N+1] float32 with [N] == 0.
    idx : [C] int32 row indices, padded with N.

    The sentinel convention makes padded gathers branch-free: padded slots
    contribute zero grad/hess/count (reference instead tracks explicit
    leaf counts via DataPartition, data_partition.hpp:17-208).
    """
    N = grad_pad.shape[0] - 1
    gb = jnp.take(bins_t, idx, axis=0)                  # [C, F]
    g = jnp.take(grad_pad, idx)
    h = jnp.take(hess_pad, idx)
    mask = (idx < N).astype(jnp.float32)
    if backend == "pallas":
        C = idx.shape[0]
        F = bins_t.shape[1]
        vals8 = jnp.zeros((8, C), jnp.float32)
        vals8 = vals8.at[0].set(g).at[1].set(h).at[2].set(mask)
        return hist_pallas(gb.T.astype(jnp.int32), vals8,
                           num_bins_padded=num_bins_padded,
                           input_dtype=input_dtype)
    vals = jnp.stack([g, h, mask])                      # [3, C]
    return hist_xla(gb.astype(jnp.int32), vals,
                    num_bins_padded=num_bins_padded, input_dtype=input_dtype)


def histogram_full_masked(bins: jax.Array, grad: jax.Array, hess: jax.Array,
                          mask: jax.Array, *, num_bins_padded: int,
                          input_dtype: str = "float32") -> jax.Array:
    """Full-scan masked histogram over ALL rows (no gather) — used by the
    fused/distributed learner where row compaction is not shard-friendly.

    bins: [F, N] (no sentinel), mask: [N] float32 0/1 row weights.
    Returns [F, 3, B] float32.
    """
    vals = jnp.stack([grad * mask, hess * mask, mask])   # [3, N]
    return hist_xla(bins.T.astype(jnp.int32), vals,
                    num_bins_padded=num_bins_padded, input_dtype=input_dtype)
