"""Histogram construction — the hottest op in the framework.

Replaces the reference's scalar CPU kernels (dense_bin.hpp:67-120) and the
OpenCL local-memory-atomic kernels (ocl/histogram{16,64,256}.cl) with a
TPU-idiomatic formulation: bins are one-hot encoded on the fly and reduced
with a matmul so the accumulation runs on the MXU — there are no fast
device atomics on TPU, but `one_hot(bins).T @ [grad, hess, 1]` is exactly a
`[B, C] @ [C, 3]` contraction (SURVEY.md §7 "hard parts").

Canonical output layout: `[F, 3, B]` float32 — (sum_grad, sum_hess, count)
per feature per bin; B is the padded per-feature bin count.  Accumulation
is fp32 (the reference GPU learner also uses single precision by default,
gpu_tree_learner.h:79-83, and reports accuracy parity).

Two implementations:
- `hist_xla`: chunked one-hot einsum, pure XLA.  Used on CPU (tests) and as
  the fallback.
- `hist_pallas`: Pallas TPU kernel; grid over (feature, row-chunk), one-hot
  built in VMEM and contracted immediately, fp32 accumulate in the output
  block across row-chunks.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np


def _pick_chunk(F: int, B: int, target_bytes: int = 1 << 26) -> int:
    """Row-chunk size so the transient one-hot stays ~64MB."""
    per_row = max(F * B * 2, 1)
    c = max(256, target_bytes // per_row)
    return int(2 ** int(np.floor(np.log2(c))))


@functools.partial(jax.jit, static_argnames=("num_bins_padded", "input_dtype"))
def hist_xla(gb: jax.Array, vals: jax.Array, *, num_bins_padded: int,
             input_dtype: str = "float32") -> jax.Array:
    """Chunked one-hot matmul histogram.

    Parameters
    ----------
    gb : [C, F] integer bin ids of the gathered rows (sentinel rows have
         arbitrary bins but zero vals).
    vals : [3, C] float32 rows (grad, hess, count-mask).
    Returns [F, 3, B] float32.
    """
    input_dtype = _coerce_dtype(input_dtype)
    C, F = gb.shape
    B = num_bins_padded
    dt = jnp.dtype(input_dtype)
    chunk = min(_pick_chunk(F, B), C)
    n_chunks = max(C // chunk, 1)
    rem = C - n_chunks * chunk

    prec = (jax.lax.Precision.HIGHEST if dt == jnp.float32
            else jax.lax.Precision.DEFAULT)

    def body(acc, args):
        gbc, vc = args  # [chunk, F], [3, chunk]
        oh = (gbc[:, :, None] == jax.lax.broadcasted_iota(
            gbc.dtype, (1, 1, B), 2)).astype(dt)
        acc = acc + jnp.einsum(
            "sc,cfb->fsb", vc.astype(dt), oh,
            preferred_element_type=jnp.float32, precision=prec)
        return acc, None

    acc0 = jnp.zeros((F, 3, B), jnp.float32)
    main = (gb[: n_chunks * chunk].reshape(n_chunks, chunk, F),
            vals[:, : n_chunks * chunk].reshape(3, n_chunks, chunk)
            .transpose(1, 0, 2))
    acc, _ = jax.lax.scan(body, acc0, main)
    if rem:
        acc, _ = body(acc, (gb[n_chunks * chunk:], vals[:, n_chunks * chunk:]))
    return acc


# ----------------------------------------------------------------------------
# Pallas TPU kernel
# ----------------------------------------------------------------------------

FEATURE_GROUP = 8  # features per kernel block (TPU second-minor tiling)


def _feature_group_from_env() -> int:
    """LGBT_FEATURE_GROUP overrides the int32-bin feature-block height
    for on-chip tuning (wide-feature shapes recompute the [Mp, Ck] vals
    block once per feature block — a taller block amortizes that over
    more features at the cost of more VMEM per grid cell).  Clamped to
    a multiple of 8 in [8, 64]."""
    try:
        v = int(_os.environ.get("LGBT_FEATURE_GROUP", "") or FEATURE_GROUP)
    except ValueError:
        return FEATURE_GROUP
    return max(8, min(64, (v // 8) * 8))

# Row-chunk length per pallas grid cell.  Larger chunks amortize grid
# overhead; VMEM per cell stays small (one-hot [CK, B] + vals [M, CK]).
# Env-tunable for on-chip experiments; parsed defensively and rounded to
# the 128-lane multiple the TPU block tiling requires.
import os as _os


def _hist_chunk_from_env(default: int) -> int:
    try:
        v = int(_os.environ.get("LGBT_HIST_CHUNK", "") or default)
    except ValueError:
        v = default
    return max(512, (v // 128) * 128)


# The gather-fed kernels keep the conservative chunk (their f32 one-hot
# transient is 4x the masked kernel's int8 ones); the masked hot-path
# kernel defaults larger — chip-measured ~6% faster per pass at 8192 —
# and self-caps by a VMEM model (see hist_multileaf_masked).
HIST_CHUNK = _hist_chunk_from_env(2048)
MASKED_HIST_CHUNK = _hist_chunk_from_env(8192)


def effective_gather_chunk(num_bins_padded: int,
                           input_dtype: str = "float32") -> int:
    """The row-chunk the gather-fed kernels ACTUALLY run (env global +
    VMEM self-cap) — for artifacts that must record the real
    configuration, not the env-derived request."""
    if input_dtype == "int8":
        input_dtype = "float32"   # gather kernels coerce (_coerce_dtype)
    isz = jnp.dtype(input_dtype).itemsize
    return min(HIST_CHUNK, _gather_chunk_cap(num_bins_padded, isz))


def _gather_chunk_cap(B: int, itemsize: int = 4) -> int:
    """VMEM self-cap for the gather-fed kernels' one-hot transient
    ([Ck, B] in the compute dtype): LGBT_HIST_CHUNK drives both chunk
    globals, so a masked-kernel sweep value (e.g. 16384) must not hand
    these kernels a ~16 MB f32 transient.  Budget 4 MB, 128-aligned.
    The floor is one 128-lane tile — a 512-row floor would let padded
    B >= 2048 blow the stated budget (512*2048*4 = 4.2 MB+); this cap
    model also sizes the gathered-segment kernel's scratch chunks."""
    cap = int(4e6) // (itemsize * max(B, 1))
    return max(128, (cap // 128) * 128)

# Narrow-dtype one-hot compare in the masked kernels (int8/bf16 instead
# of int32 — see _packed_onehot).  Kill-switch for on-chip A/B.
NARROW_ONEHOT = _os.environ.get("LGBT_NARROW_ONEHOT", "1") != "0"


def disable_narrow_onehot():
    """Runtime fallback if a TPU generation's Mosaic rejects an int8
    vector op the narrow paths assume: flip the flag AND drop this
    module's compiled traces (the flag is read at trace time, so a
    stale cache would keep returning the narrow program).  Callers
    must rebuild their own jitted closures (e.g. recreate the Booster)."""
    global NARROW_ONEHOT
    NARROW_ONEHOT = False
    hist_multileaf_masked.clear_cache()
    hist_pallas.clear_cache()
    hist_pallas_multileaf.clear_cache()


def _coerce_dtype(input_dtype: str) -> str:
    """int8 means caller-side gradient quantization, which only the
    rounds learner's kernels implement (the dense masked kernel and the
    sparse XLA/pallas pair); a bare int8 cast would TRUNCATE real-valued
    grads, so every other kernel runs f32 and says so (the warning fires
    once per compile, at trace time)."""
    if input_dtype == "int8":
        from .. import log
        # graftlint: allow(retrace-hazard) — deliberate ONE-shot warning at trace time (static branch, never re-fires per iteration)
        log.warning("histogram_dtype=int8 is only supported by the "
                    "batched-rounds learner; using float32 here")
        return "float32"
    return input_dtype



def _hist_kernel(gb_ref, vals_ref, out_ref, *, B: int, input_dtype):
    """One (feature-group, row-chunk) grid cell.

    gb_ref: [1, G, Ck] int32 bins of G features for this row chunk
    vals_ref: [8, Ck] float32 (grad, hess, mask, 5 pad rows)
    out_ref: [1, G, 8, B] float32 accumulated across the chunk grid axis

    TPU block shapes need the last two dims (8|16|32, 128)-aligned
    (pallas guide "tiling"): grouping G=8 features per block keeps every
    ref legal, and the G one-hot matmuls unroll inside the kernel.
    """
    from jax.experimental import pallas as pl

    k = pl.program_id(1)

    @pl.when(k == 0)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    vals = vals_ref[:].astype(input_dtype)      # [8, Ck]
    # f32 inputs get full-precision (3-pass) MXU matmuls; bf16 runs fast
    prec = (jax.lax.Precision.HIGHEST if input_dtype == jnp.float32
            else jax.lax.Precision.DEFAULT)
    G = gb_ref.shape[1]
    for g in range(G):
        oh = _simple_onehot(gb_ref[0, g, :], B, input_dtype)  # [Ck, B]
        out_ref[0, g, :, :] += jnp.dot(
            vals, oh, preferred_element_type=jnp.float32, precision=prec)


@functools.partial(jax.jit, static_argnames=("num_bins_padded", "input_dtype",
                                             "interpret"))
def hist_pallas(gb_t: jax.Array, vals8: jax.Array, *, num_bins_padded: int,
                input_dtype: str = "bfloat16",
                interpret: bool = False) -> jax.Array:
    """Pallas histogram.  gb_t: [F, C] int32, vals8: [8, C] float32.

    Returns [F, 3, B] float32.
    """
    input_dtype = _coerce_dtype(input_dtype)
    from jax.experimental import pallas as pl

    F, C = gb_t.shape
    B = num_bins_padded
    G = FEATURE_GROUP
    Ck = min(C, HIST_CHUNK, _gather_chunk_cap(B, jnp.dtype(input_dtype).itemsize))
    if C % Ck:
        # pad rows to a chunk multiple; padded slots have zero vals so they
        # contribute nothing to any bin
        pad = Ck - C % Ck
        gb_t = jnp.pad(gb_t, ((0, 0), (0, pad)))
        vals8 = jnp.pad(vals8, ((0, 0), (0, pad)))
        C += pad
    Fg = G * ((F + G - 1) // G)
    if Fg > F:
        gb_t = jnp.pad(gb_t, ((0, Fg - F), (0, 0)))
    gb_g = gb_t.reshape(Fg // G, G, C)
    grid = (Fg // G, C // Ck)
    dt = jnp.dtype(input_dtype)

    out = pl.pallas_call(
        functools.partial(_hist_kernel, B=B, input_dtype=dt),
        out_shape=jax.ShapeDtypeStruct((Fg // G, G, 8, B), jnp.float32),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, G, Ck), lambda f, k: (f, 0, k)),
            pl.BlockSpec((8, Ck), lambda f, k: (0, k)),
        ],
        out_specs=pl.BlockSpec((1, G, 8, B), lambda f, k: (f, 0, 0, 0)),
        interpret=interpret,
    )(gb_g, vals8)
    return out.reshape(Fg, 8, B)[:F, :3, :]


def _hist_kernel_ml(gb_ref, vals_ref, out_ref, *, B: int, input_dtype):
    """Multi-leaf variant: vals carries M = 3·K channel rows (grad, hess,
    mask for K leaves), so one pass over the rows histograms K leaves at
    once — the M dimension of the MXU matmul is what the per-leaf version
    wastes (M=8, ~6% utilization); at M=128 the systolic array is full.

    gb_ref: [1, G, Ck] int32 ; vals_ref: [M, Ck] f32 ; out_ref: [1, G, M, B]
    """
    from jax.experimental import pallas as pl

    k = pl.program_id(1)

    @pl.when(k == 0)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    vals = vals_ref[:].astype(input_dtype)
    prec = (jax.lax.Precision.HIGHEST if input_dtype == jnp.float32
            else jax.lax.Precision.DEFAULT)
    G = gb_ref.shape[1]
    for g in range(G):
        oh = _simple_onehot(gb_ref[0, g, :], B, input_dtype)
        out_ref[0, g, :, :] += jnp.dot(
            vals, oh, preferred_element_type=jnp.float32, precision=prec)


@functools.partial(jax.jit, static_argnames=("num_bins_padded", "input_dtype",
                                             "interpret"))
def hist_pallas_multileaf(gb_t: jax.Array, vals: jax.Array, *,
                          num_bins_padded: int,
                          input_dtype: str = "bfloat16",
                          interpret: bool = False) -> jax.Array:
    """Multi-leaf pallas histogram.  gb_t: [F, C] int, vals: [M, C] f32
    (M a multiple of 8, ≤ 128).  Returns [F, M, B] f32."""
    input_dtype = _coerce_dtype(input_dtype)
    from jax.experimental import pallas as pl

    F, C = gb_t.shape
    M = vals.shape[0]
    B = num_bins_padded
    G = FEATURE_GROUP
    Ck = min(C, HIST_CHUNK, _gather_chunk_cap(B, jnp.dtype(input_dtype).itemsize))
    if C % Ck:
        pad = Ck - C % Ck
        gb_t = jnp.pad(gb_t, ((0, 0), (0, pad)))
        vals = jnp.pad(vals, ((0, 0), (0, pad)))
        C += pad
    Fg = G * ((F + G - 1) // G)
    if Fg > F:
        gb_t = jnp.pad(gb_t, ((0, Fg - F), (0, 0)))
    gb_g = gb_t.reshape(Fg // G, G, C).astype(jnp.int32)
    grid = (Fg // G, C // Ck)
    dt = jnp.dtype(input_dtype)

    out = pl.pallas_call(
        functools.partial(_hist_kernel_ml, B=B, input_dtype=dt),
        out_shape=jax.ShapeDtypeStruct((Fg // G, G, M, B), jnp.float32),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, G, Ck), lambda f, k: (f, 0, k)),
            pl.BlockSpec((M, Ck), lambda f, k: (0, k)),
        ],
        out_specs=pl.BlockSpec((1, G, M, B), lambda f, k: (f, 0, 0, 0)),
        interpret=interpret,
    )(gb_g, vals)
    return out.reshape(Fg, M, B)[:F]


def hist_multileaf_xla(gb_t: jax.Array, vals: jax.Array, *,
                       num_bins_padded: int,
                       input_dtype: str = "float32") -> jax.Array:
    """XLA fallback for the multi-leaf histogram (CPU tests / non-TPU).
    gb_t: [F, C] int, vals: [M, C] f32 → [F, M, B] f32."""
    input_dtype = _coerce_dtype(input_dtype)
    B = num_bins_padded
    dt = jnp.dtype(input_dtype)
    prec = (jax.lax.Precision.HIGHEST if dt == jnp.float32
            else jax.lax.Precision.DEFAULT)
    C = gb_t.shape[1]
    chunk = min(C, 1 << 16)
    n_chunks = (C + chunk - 1) // chunk
    if C % chunk:
        pad = chunk * n_chunks - C
        gb_t = jnp.pad(gb_t, ((0, 0), (0, pad)))
        vals = jnp.pad(vals, ((0, 0), (0, pad)))

    def body(acc, args):
        gbc, vc = args  # [F, chunk], [M, chunk]
        oh = (gbc[:, :, None] == jax.lax.broadcasted_iota(
            jnp.int32, (1, 1, B), 2)).astype(dt)
        return acc + jnp.einsum("mc,fcb->fmb", vc.astype(dt), oh,
                                preferred_element_type=jnp.float32,
                                precision=prec), None

    F = gb_t.shape[0]
    M = vals.shape[0]
    acc0 = jnp.zeros((F, M, B), jnp.float32)
    gbs = gb_t.reshape(F, n_chunks, chunk).transpose(1, 0, 2).astype(jnp.int32)
    vs = vals.reshape(M, n_chunks, chunk).transpose(1, 0, 2)
    acc, _ = jax.lax.scan(body, acc0, (gbs, vs))
    return acc


def hist_multileaf(gb_t: jax.Array, vals: jax.Array, *, num_bins_padded: int,
                   backend: str = "xla",
                   input_dtype: str = "float32") -> jax.Array:
    if backend == "pallas":
        return hist_pallas_multileaf(gb_t, vals,
                                     num_bins_padded=num_bins_padded,
                                     input_dtype=input_dtype)
    return hist_multileaf_xla(gb_t, vals, num_bins_padded=num_bins_padded,
                              input_dtype=input_dtype)


def _simple_onehot(gb, B, input_dtype):
    """Unpacked one-hot for the gather-fed kernels: the compare runs in
    bf16 when the output is bf16 (2x the int32 VPU lane volume; bins
    <= 255 are bf16-exact — gated on B <= 256), else in int32."""
    iota = jax.lax.broadcasted_iota(jnp.int32, (1, B), 1)
    if input_dtype == jnp.bfloat16 and NARROW_ONEHOT and B <= 256:
        return (gb.astype(jnp.bfloat16)[:, None]
                == iota.astype(jnp.bfloat16)).astype(jnp.bfloat16)
    return (gb[:, None] == iota).astype(input_dtype)


def _packed_onehot(gb_ref, g_, B, pack, bins_sub, out_dtype,
                   bin_offset=0, bwin=0, narrow=False):
    """One-hot block for `pack` features sharing the 128 lanes: feature
    s of the pack occupies lanes [s·bins_sub, (s+1)·bins_sub), so ONE
    [M, Ck] @ [Ck, B] matmul histograms all `pack` features — the fix
    for the 2x bin-axis padding tax at max_bin<=63 (the reference GPU
    sweet spot, docs/GPU-Performance.md:153-156): without packing a
    64-bin histogram still pays full 128-lane MXU work.

    bin_offset: bins may arrive stored as int8 `bin - 128` (the HBM
    layout that fits Expo-scale 11M x 700 on one chip); the widen +
    un-offset runs here in VMEM, never materializing wide bins.

    bwin: first bin of this grid cell's output window (the bin axis may
    be split across a grid dimension so the per-cell output block stays
    one 128-lane tile — the full [G, Mp, 256] block double-buffers to
    16 MB and overflows VMEM on multi-feature-block grids).  B here is
    the WINDOW width (the out block's lane count), not the full bin
    count.

    narrow: run the [Ck, B] equality in the NARROWEST dtype holding the
    bin domain instead of int32.  This compare (plus its cast to the
    matmul operand dtype) is the dominant per-pass cost at north-star
    shape — the pass is VPU-bound, not MXU-bound: K=1 costs 207 ms vs
    214 ms at K=128 (profile_hotpath_measured.json).  int8 tiles are
    (32, 128) = 4x the int32 lane volume per op, and select replaces
    the bool→int32→int8 double cast.  Exactness: every shifted operand
    (bin + s·bins_sub, lane + bwin, both shifted by -128) lies in ONE
    256-wide window, so mod-256 int8 equality IS value equality — the
    caller sets narrow only when the full bin count <= 256."""
    iota = jax.lax.broadcasted_iota(jnp.int32, (1, B), 1) + bwin
    if narrow and out_dtype == jnp.int8:
        # int8 compare domain: x - 128 for every operand
        iota8 = (iota - 128).astype(jnp.int8)
        acc = None
        for s in range(pack):
            gb = gb_ref[0, g_ * pack + s, :]
            if gb.dtype == jnp.int8:
                # stored value-128 already; the pack shift cannot
                # overflow: value-128 < bins_sub-128 <= -64, shift <= 96
                if s:
                    gb = gb + jnp.int8(s * bins_sub)
            else:
                gb = (gb + (s * bins_sub - 128)).astype(jnp.int8)
            cmp = gb[:, None] == iota8
            acc = cmp if acc is None else acc | cmp
        return jnp.where(acc, jnp.int8(1), jnp.int8(0))
    if narrow and out_dtype == jnp.bfloat16:
        # bf16 tiles are (16, 128) = 2x int32; bins <= 255 are exact
        iotab = iota.astype(jnp.bfloat16)
        acc = None
        for s in range(pack):
            gb = gb_ref[0, g_ * pack + s, :].astype(jnp.int32) + bin_offset
            cmp = (gb + (s * bins_sub)).astype(jnp.bfloat16)[:, None] == iotab
            acc = cmp if acc is None else acc | cmp
        return acc.astype(jnp.bfloat16)
    acc = None
    for s in range(pack):
        gb = gb_ref[0, g_ * pack + s, :].astype(jnp.int32) + bin_offset
        cmp = (gb[:, None] + (s * bins_sub)) == iota
        acc = cmp if acc is None else acc | cmp
    if out_dtype == jnp.int8:
        return acc.astype(jnp.int32).astype(jnp.int8)
    return acc.astype(out_dtype)


def _hist_kernel_masked(sl_ref, gb_ref, lid_ref, gh_ref, out_ref, *,
                        B: int, K: int, input_dtype, pack: int = 1,
                        bins_sub: int = 0, bin_offset: int = 0,
                        windowed: bool = False, narrow: bool = False):
    """Multi-leaf histogram with the leaf masks built in VMEM.

    sl_ref : [Kp, 128] int32 — small-leaf id per slot, replicated across
             lanes (-1 for empty slots, matches nothing)
    gb_ref : [1, G, Ck] int32, or int8 holding value-128 when
             bin_offset=128 (widened per feature row in _packed_onehot)
    lid_ref: [1, Ck] int32 leaf id per row
    gh_ref : [8, Ck] f32 rows (grad·rm, hess·rm, rm, pad…)
    out_ref: [1, G/pack, Mp, B] f32 — rows [0:K)=grad, [K:2K)=hess,
             [2K:3K)=count; with pack>1 each lane block holds `pack`
             features' bins_sub-wide histograms side by side

    Fusing the mask construction here avoids materializing the [3K, N]
    values matrix in HBM per chunk (the XLA-level formulation round-trips
    ~0.5 GB per histogram pass at N=1M).

    Grid is (feature-blocks, row-chunks), or (feature-blocks,
    bin-windows, row-chunks) when `windowed` — the out block then
    covers one 128-lane bin window.
    """
    from jax.experimental import pallas as pl

    if windowed:
        k = pl.program_id(2)
        bwin = pl.program_id(1) * out_ref.shape[3]
    else:
        k = pl.program_id(1)
        bwin = 0
    Bs = out_ref.shape[3]

    @pl.when(k == 0)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    lid = lid_ref[0, :]                                  # [Ck]
    sl = sl_ref[:K, 0:1]                                 # [K, 1]
    m = (lid[None, :] == sl).astype(input_dtype)         # [K, Ck]
    g = gh_ref[0:1, :].astype(input_dtype)
    h = gh_ref[1:2, :].astype(input_dtype)
    rm = gh_ref[2:3, :].astype(input_dtype)
    vals = jnp.concatenate([m * g, m * h, m * rm], axis=0)   # [3K, Ck]
    Mp = out_ref.shape[2]
    if Mp > 3 * K:
        vals = jnp.concatenate(
            [vals, jnp.zeros((Mp - 3 * K, vals.shape[1]), input_dtype)],
            axis=0)
    prec = (jax.lax.Precision.HIGHEST if input_dtype == jnp.float32
            else jax.lax.Precision.DEFAULT)
    G = gb_ref.shape[1]
    for g_ in range(G // pack):
        oh = _packed_onehot(gb_ref, g_, Bs, pack, bins_sub, input_dtype,
                            bin_offset, bwin, narrow)
        out_ref[0, g_, :, :] += jnp.dot(
            vals, oh, preferred_element_type=jnp.float32, precision=prec)


def _hist_kernel_masked_q(sl_ref, gb_ref, lid_ref, ghq_ref, out_ref, *,
                          B: int, K: int, pack: int = 1,
                          bins_sub: int = 0, bin_offset: int = 0,
                          windowed: bool = False, narrow: bool = False,
                          narrow_lid: bool = False):
    """int8-quantized variant of _hist_kernel_masked: vals and one-hot
    are int8 and the contraction accumulates exactly in int32 (v5e runs
    int8 MXU matmuls at 2x bf16 throughput).  ghq rows are pre-quantized
    (round(grad/scale_g), round(hess/scale_h), 0/1 mask) stored widened
    as int32; dequantization happens in the caller.  Every product is
    exact: masks are 0/1 and |q| <= 127.  Accumulation is exact while
    127 * rows_per_device < 2^31 — the caller enforces a 16M-row bound
    and falls back to bfloat16 beyond it.  Grid as in
    _hist_kernel_masked (bin-window axis only when `windowed`)."""
    from jax.experimental import pallas as pl

    if windowed:
        k = pl.program_id(2)
        bwin = pl.program_id(1) * out_ref.shape[3]
    else:
        k = pl.program_id(1)
        bwin = 0
    Bs = out_ref.shape[3]

    @pl.when(k == 0)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    Mp = out_ref.shape[2]
    if narrow_lid:
        # leaf-id compare and mask-select natively in int8 ((32, 128)
        # VPU tiles = 4x the int32 lane volume; a where replaces the
        # int32 multiply + narrowing cast).  Exact while leaf ids fit
        # one 256-window after the -128 shift: the caller gates on
        # num_leaves <= 255, so live ids map to [-128, 126] and the
        # empty-slot sentinel -1 wraps to 127, which no live id takes.
        # Padded rows (lid sentinel -2 wraps to 126 = id 254's code)
        # carry all-zero ghq rows, so an aliased mask hit contributes 0.
        lid8 = (lid_ref[0, :] - 128).astype(jnp.int8)
        sl8 = (sl_ref[:K, 0:1] - 128).astype(jnp.int8)
        cmp = lid8[None, :] == sl8                       # [K, Ck]
        z = jnp.int8(0)
        parts = [jnp.where(cmp, ghq_ref[r:r + 1, :].astype(jnp.int8), z)
                 for r in range(3)]
        if Mp > 3 * K:
            parts.append(jnp.zeros((Mp - 3 * K, cmp.shape[1]), jnp.int8))
        vals = jnp.concatenate(parts, axis=0)            # [Mp, Ck] int8
    else:
        lid = lid_ref[0, :]
        sl = sl_ref[:K, 0:1]
        # elementwise mask work stays in i32 (Mosaic has neither int8
        # 'arith.muli' nor an i1->(32,128)-tile relayout on this target);
        # only the matmul OPERANDS are int8 — that is where the 2x
        # throughput lives, and i32->i8 truncation is a supported cast
        m = (lid[None, :] == sl).astype(jnp.int32)       # [K, Ck]
        vals32 = jnp.concatenate([m * ghq_ref[0:1, :], m * ghq_ref[1:2, :],
                                  m * ghq_ref[2:3, :]], axis=0)  # [3K, Ck]
        if Mp > 3 * K:
            vals32 = jnp.concatenate(
                [vals32, jnp.zeros((Mp - 3 * K, vals32.shape[1]),
                                   jnp.int32)], axis=0)
        vals = vals32.astype(jnp.int8)
    G = gb_ref.shape[1]
    for g_ in range(G // pack):
        oh = _packed_onehot(gb_ref, g_, Bs, pack, bins_sub, jnp.int8,
                            bin_offset, bwin, narrow)
        out_ref[0, g_, :, :] += jnp.dot(
            vals, oh, preferred_element_type=jnp.int32)


def _quantize_gh(gh8):
    """Per-pass symmetric int8 quantization of the grad/hess rows.
    Returns (ghq [8, C] int32 holding int8-ranged values, scale_g,
    scale_h).  The mask row is carried through exactly (0/1)."""
    sg = jnp.maximum(jnp.max(jnp.abs(gh8[0])), 1e-30) / 127.0
    sh = jnp.maximum(jnp.max(jnp.abs(gh8[1])), 1e-30) / 127.0
    ghq = jnp.concatenate([
        jnp.round(gh8[0:1] / sg), jnp.round(gh8[1:2] / sh), gh8[2:3],
        jnp.zeros_like(gh8[3:])], axis=0).astype(jnp.int32)
    return ghq, sg, sh


def packed_bins_layout(max_num_bin: int, num_bins_padded: int):
    """(bins_sub, pack) for the feature-packing optimization: when every
    feature has <= 64 bins, `pack` features share one 128-lane block so
    the one-hot matmul does no padded-lane work (docs/GPU-Performance.md
    :153-156 — max_bin=63 is the accelerator sweet spot the reference
    serves with a dedicated histogram64 kernel).  (0, 1) = no packing."""
    if num_bins_padded != 128 or max_num_bin <= 0:
        return 0, 1
    for bs in (16, 32, 64):
        if max_num_bin <= bs:
            return bs, 128 // bs
    return 0, 1


@functools.partial(jax.jit, static_argnames=("num_bins_padded", "backend",
                                             "input_dtype", "interpret",
                                             "max_num_bin", "num_leaves"))
def hist_multileaf_masked(gb_t: jax.Array, lid: jax.Array, gh8: jax.Array,
                          sl: jax.Array, *, num_bins_padded: int,
                          backend: str = "xla",
                          input_dtype: str = "float32",
                          interpret: bool = False,
                          max_num_bin: int = 0,
                          num_leaves: int = 0) -> jax.Array:
    """Histogram K leaves in one pass, masks built on the fly.

    gb_t: [F, C] int bins; lid: [C] int32 leaf ids; gh8: [8, C] f32
    (grad·rm, hess·rm, rm, pads); sl: [K] int32 leaf ids to histogram
    (-1 = empty slot).  Returns [K, F, 3, B] f32.

    max_num_bin (static; 0 = unknown) enables feature packing on the
    pallas path when all bins fit a 16/32/64-lane sub-block.

    num_leaves (static; 0 = unknown): the leaf COUNT — an EXCLUSIVE
    bound on leaf ids (ids < num_leaves; an id equal to num_leaves=255
    would wrap onto the empty-slot sentinel).  When <= 255 the
    quantized kernel runs the leaf-id mask compare in int8 (see
    _hist_kernel_masked_q narrow_lid).

    input_dtype "int8" (the validated bench default) selects per-pass symmetric
    gradient quantization with exact int32 accumulation: counts are
    exact, grad/hess entries carry <= |max|/254 absolute rounding error
    each — far finer than LightGBM-4-style 2-5 bit quantized training.
    The XLA fallback emulates the same dequantized values so CPU runs
    reproduce the TPU behavior.
    """
    from jax.experimental import pallas as pl

    F, C = gb_t.shape
    K = sl.shape[0]
    B = num_bins_padded
    quant = input_dtype == "int8"
    # int8-STORED bins (value - 128): the HBM layout that fits wide
    # datasets (Expo 11M x 700 = 7.7 GB instead of 30.8 GB int32); the
    # pallas path widens blocks in VMEM, the XLA path fuses the widen
    bin_offset = 128 if gb_t.dtype == jnp.int8 else 0
    # int32-accumulator safety: with constant hessians every row
    # quantizes to exactly 127, so one bin can accumulate 127*C — keep
    # 127*C < 2^31 (and per-bin counts < 2^24 so the f32 conversion
    # stays exact).  Shapes are static, so this resolves at trace time.
    if quant and C > 16_000_000:
        from .. import log
        # graftlint: allow(retrace-hazard) — deliberate ONE-shot warning at trace time (shape is static, fires once per compile)
        log.warning("histogram_dtype=int8 disabled for this pass: "
                    f"{C} rows exceeds the int32-exactness bound "
                    "(16M rows per device); using bfloat16")
        quant = False
        input_dtype = "bfloat16"

    if backend != "pallas":
        if bin_offset:
            gb_t = gb_t.astype(jnp.int32) + bin_offset
        if quant:
            ghq, sg, sh = _quantize_gh(gh8)
            gh8 = jnp.concatenate([
                ghq[0:1].astype(jnp.float32) * sg,
                ghq[1:2].astype(jnp.float32) * sh,
                gh8[2:3], gh8[3:]], axis=0)
            input_dtype = "float32"
        m = (lid[None, :] == sl[:, None]).astype(jnp.float32)
        vals = jnp.concatenate(
            [m * gh8[0:1], m * gh8[1:2], m * gh8[2:3]], axis=0)  # [3K, C]
        h = hist_multileaf_xla(gb_t, vals, num_bins_padded=B,
                               input_dtype=input_dtype)          # [F, 3K, B]
        return jnp.stack([h[:, :K], h[:, K:2 * K], h[:, 2 * K:3 * K]],
                         axis=2).transpose(1, 0, 2, 3)

    # int8 bins keep their narrow dtype into the kernel; the int8 VMEM
    # tile is (32, 128), so the feature-group sublane dim grows to 32.
    # The int32 path reads LGBT_FEATURE_GROUP (process-start value: the
    # flag is trace-time, like the narrow-kernel switches)
    G = 32 if bin_offset else _feature_group_from_env()
    Ck = min(C, MASKED_HIST_CHUNK)
    if bin_offset:
        # the G=32 layout quadruples the per-cell output block
        # (G·Mp·B·4 at B=256 double-buffers past the 16 MB VMEM scope
        # with long row chunks); keep the chip-validated chunk
        Ck = min(Ck, 2048)
    else:
        # cap the big per-chunk transients — the [Mp, Ck] vals
        # intermediate plus the [Ck, B] one-hot — at ~15 MB, the
        # measured VMEM ceiling: Mp=256/Ck=16384 int32 vals (16.8 MB
        # alone) OOMs on chip, Mp=384/Ck=8192 (12.6 + 2 MB) fits.  The
        # narrow-lid quant path never materializes int32 vals (the
        # where-select emits int8 directly), so its rows are ~4x
        # cheaper and admit a larger LGBT_HIST_CHUNK.
        Mp_ = 8 * ((3 * K + 7) // 8)
        isz = jnp.dtype(input_dtype).itemsize
        if quant:
            vals_b = Mp_ * (1 if (NARROW_ONEHOT and 0 < num_leaves <= 255)
                            else 4)
            per_row = vals_b + B
        else:
            per_row = Mp_ * isz + B * isz
        Ck = min(Ck, max(512, (int(15e6) // per_row) // 128 * 128))
    if C % Ck:
        pad = Ck - C % Ck
        gb_t = jnp.pad(gb_t, ((0, 0), (0, pad)))
        lid = jnp.pad(lid, (0, pad), constant_values=-2)
        gh8 = jnp.pad(gh8, ((0, 0), (0, pad)))
        C += pad
    Fg = G * ((F + G - 1) // G)
    if Fg > F:
        gb_t = jnp.pad(gb_t, ((0, Fg - F), (0, 0)))
    gb_g = gb_t.reshape(Fg // G, G, C)
    if not bin_offset:
        gb_g = gb_g.astype(jnp.int32)
    Mp = 8 * ((3 * K + 7) // 8)
    Kp = 8 * ((K + 7) // 8)
    sl2 = jnp.broadcast_to(jnp.pad(sl, (0, Kp - K),
                                   constant_values=-1)[:, None], (Kp, 128))
    bins_sub, pack = packed_bins_layout(max_num_bin, B)
    Gp = G // pack
    # bin windows: one 128-lane output block per grid cell.  The full
    # [1, Gp, Mp, 256] block is 8 MB at G=32 and double-buffers to 16 MB
    # across feature blocks — over the VMEM scope.  Splitting the bin
    # axis over the grid keeps the block one lane-tile wide; the one-hot
    # compare is redone per window (cheap), the matmul work is unchanged.
    nB = B // 128 if (bin_offset and B > 128) else 1
    Bs = B // nB
    if nB > 1:
        grid = (Fg // G, nB, C // Ck)
        in_specs = [
            pl.BlockSpec((Kp, 128), lambda f, b, k: (0, 0)),
            pl.BlockSpec((1, G, Ck), lambda f, b, k: (f, 0, k)),
            pl.BlockSpec((1, Ck), lambda f, b, k: (0, k)),
            pl.BlockSpec((8, Ck), lambda f, b, k: (0, k)),
        ]
        out_spec = pl.BlockSpec((1, Gp, Mp, Bs),
                                lambda f, b, k: (f, 0, 0, b))
    else:
        # keep the plain 2-axis grid when no windowing is needed: the
        # singleton middle axis measurably deoptimized Mosaic's
        # pipelining (learner-level 2.5x at Epsilon 63-bin)
        grid = (Fg // G, C // Ck)
        in_specs = [
            pl.BlockSpec((Kp, 128), lambda f, k: (0, 0)),
            pl.BlockSpec((1, G, Ck), lambda f, k: (f, 0, k)),
            pl.BlockSpec((1, Ck), lambda f, k: (0, k)),
            pl.BlockSpec((8, Ck), lambda f, k: (0, k)),
        ]
        out_spec = pl.BlockSpec((1, Gp, Mp, Bs), lambda f, k: (f, 0, 0, 0))

    def unpack(out):
        """[Fg/G, G/pack, Mp, B] kernel output -> [F, Mp, B] with each
        packed feature's bins_sub-wide histogram moved back to lanes
        [0, bins_sub) and the bin axis zero-padded to B (bins >= the
        sub-block width never occur, so zero is exact)."""
        if pack == 1:
            return out.reshape(Fg, Mp, B)[:F]
        h = out.reshape(Fg // G, Gp, Mp, pack, bins_sub)
        h = h.transpose(0, 1, 3, 2, 4).reshape(Fg, Mp, bins_sub)
        return jnp.pad(h, ((0, 0), (0, 0), (0, B - bins_sub)))[:F]

    # narrow compare is exact only while every operand fits one 256-wide
    # window (see _packed_onehot); B > 256 would alias mod 256.  The
    # leaf-id compare narrows under the same window argument when the
    # caller states num_leaves <= 255 (0 = unknown, stay wide).
    narrow = NARROW_ONEHOT and B <= 256
    narrow_lid = NARROW_ONEHOT and 0 < num_leaves <= 255

    if quant:
        ghq, sg, sh = _quantize_gh(gh8)
        out = pl.pallas_call(
            functools.partial(_hist_kernel_masked_q, B=B, K=K, pack=pack,
                              bins_sub=bins_sub, bin_offset=bin_offset,
                              windowed=nB > 1, narrow=narrow,
                              narrow_lid=narrow_lid),
            out_shape=jax.ShapeDtypeStruct((Fg // G, Gp, Mp, B), jnp.int32),
            grid=grid,
            in_specs=in_specs,
            out_specs=out_spec,
            interpret=interpret,
        )(sl2, gb_g, lid[None, :], ghq)
        h = unpack(out).astype(jnp.float32)
        return jnp.stack([h[:, :K] * sg, h[:, K:2 * K] * sh,
                          h[:, 2 * K:3 * K]],
                         axis=2).transpose(1, 0, 2, 3)

    dt = jnp.dtype(input_dtype)
    out = pl.pallas_call(
        functools.partial(_hist_kernel_masked, B=B, K=K, input_dtype=dt,
                          pack=pack, bins_sub=bins_sub,
                          bin_offset=bin_offset, windowed=nB > 1,
                          narrow=narrow),
        out_shape=jax.ShapeDtypeStruct((Fg // G, Gp, Mp, B), jnp.float32),
        grid=grid,
        in_specs=in_specs,
        out_specs=out_spec,
        interpret=interpret,
    )(sl2, gb_g, lid[None, :], gh8)
    h = unpack(out)                                      # [F, Mp, B]
    return jnp.stack([h[:, :K], h[:, K:2 * K], h[:, 2 * K:3 * K]],
                     axis=2).transpose(1, 0, 2, 3)


# ----------------------------------------------------------------------------
# Public entry: gather + histogram
# ----------------------------------------------------------------------------

def histogram_from_indices(bins_t: jax.Array, grad_pad: jax.Array,
                           hess_pad: jax.Array, idx: jax.Array, *,
                           num_bins_padded: int, backend: str = "xla",
                           input_dtype: str = "float32") -> jax.Array:
    """hist [F, 3, B] over the rows named by `idx`.

    bins_t : [N+1, F] integer bins, row N is the sentinel (any value).
    grad_pad, hess_pad : [N+1] float32 with [N] == 0.
    idx : [C] int32 row indices, padded with N.

    The sentinel convention makes padded gathers branch-free: padded slots
    contribute zero grad/hess/count (reference instead tracks explicit
    leaf counts via DataPartition, data_partition.hpp:17-208).
    """
    N = grad_pad.shape[0] - 1
    gb = jnp.take(bins_t, idx, axis=0)                  # [C, F]
    g = jnp.take(grad_pad, idx)
    h = jnp.take(hess_pad, idx)
    mask = (idx < N).astype(jnp.float32)
    if backend == "pallas":
        C = idx.shape[0]
        F = bins_t.shape[1]
        vals8 = jnp.zeros((8, C), jnp.float32)
        vals8 = vals8.at[0].set(g).at[1].set(h).at[2].set(mask)
        return hist_pallas(gb.T.astype(jnp.int32), vals8,
                           num_bins_padded=num_bins_padded,
                           input_dtype=input_dtype)
    vals = jnp.stack([g, h, mask])                      # [3, C]
    return hist_xla(gb.astype(jnp.int32), vals,
                    num_bins_padded=num_bins_padded, input_dtype=input_dtype)


def gather_segments(perm: jax.Array, seg_off: jax.Array,
                    seg_cnt: jax.Array, *, capacity: int):
    """Concatenate K contiguous segments of the row permutation `perm`
    into one static scratch layout (the reference's ordered-gradients
    read: DataPartition keeps each leaf's rows contiguous and the
    histogram kernel walks exactly that span,
    data_partition.hpp:80-130).

    perm : [N] int32 row permutation (rows grouped by leaf).
    seg_off, seg_cnt : [K] int32 — segment start/length per slot inside
        `perm` (cnt 0 = empty slot).
    capacity : static scratch length; must satisfy sum(seg_cnt) <=
        capacity (callers size it from the N/2 smaller-child bound).

    Returns (idx [capacity] int32 row ids — clamped-but-arbitrary for
    unused scratch slots, slot [capacity] int32 slot id per scratch
    position with -2 marking unused slots, total int32 scalar).
    """
    K = seg_off.shape[0]
    base = jnp.concatenate([jnp.zeros(1, jnp.int32),
                            jnp.cumsum(seg_cnt.astype(jnp.int32))])  # [K+1]
    total = base[K]
    j = jax.lax.iota(jnp.int32, capacity)
    # scratch position j belongs to the slot whose cumulative span
    # contains it; empty slots span nothing and are never selected
    slot = jnp.searchsorted(base[1:], j, side="right").astype(jnp.int32)
    valid = j < total
    sc = jnp.minimum(slot, K - 1)
    pos = seg_off[sc] + (j - base[sc])
    pos = jnp.clip(pos, 0, perm.shape[0] - 1)
    idx = jnp.take(perm, pos)
    return idx, jnp.where(valid, sc, -2), total


def hist_multileaf_gathered(bins_fn: jax.Array, gh8: jax.Array,
                            perm: jax.Array, seg_off: jax.Array,
                            seg_cnt: jax.Array, *, capacity: int,
                            num_bins_padded: int, backend: str = "xla",
                            input_dtype: str = "float32",
                            interpret: bool = False,
                            max_num_bin: int = 0) -> jax.Array:
    """Histogram K leaf-contiguous row segments in one pass over a
    static [capacity] scratch — the "ordered" alternative to
    hist_multileaf_masked that touches only the rows the round needs
    instead of streaming all N.

    bins_fn : [F, N] int bins (int8 = value-128 storage, kept narrow
        through the gather); gh8 : [8, N] f32 (grad·rm, hess·rm, rm,
        pads); perm/seg_off/seg_cnt as gather_segments.  Everything here
        is shard-local: under shard_map the caller passes its own row
        block's permutation and segment tables, and the returned local
        histograms are exchanged (psum / psum_scatter) afterwards.

    Returns [K, F, 3, B] f32 — slot k holds segment k's histogram
    (exactly hist_multileaf_masked's output for the same leaf when the
    segment contains that leaf's live rows; empty slots are zero).

    The heavy lifting reuses the masked kernel pair (incl. the int8
    one-hot Pallas path) on the compacted rows: scratch slot ids play
    the leaf-id role, so nothing about the VMEM mask-building or the
    quantized int32 accumulation changes — only C collapses from N to
    `capacity`.  `capacity` is static, so repeated calls at the same
    tier never retrace.  On the int8 path the per-pass quantization
    scales derive from the gathered rows only (a tighter bound than the
    masked kernel's all-rows max — strictly less rounding error)."""
    K = seg_off.shape[0]
    idx, slot, _ = gather_segments(perm, seg_off, seg_cnt,
                                   capacity=capacity)
    gbg = jnp.take(bins_fn, idx, axis=1)             # [F, capacity]
    live = (slot >= 0)
    ghg = jnp.take(gh8, idx, axis=1) * live[None, :].astype(jnp.float32)
    sl = jax.lax.iota(jnp.int32, K)
    # the in-kernel "leaf" ids are the slot ids, so the narrow-compare
    # gate is the slot count (exclusive bound on every live lid)
    return hist_multileaf_masked(gbg, slot, ghg, sl,
                                 num_bins_padded=num_bins_padded,
                                 backend=backend, input_dtype=input_dtype,
                                 interpret=interpret,
                                 max_num_bin=max_num_bin,
                                 num_leaves=K if K <= 255 else 0)


# ----------------------------------------------------------------------------
# Sparse (nonzero-iterating) histogram pair — docs/Sparse.md
#
# The store is CSR/ELL-packed: each row carries up to R (column id, bin)
# entries for the cells whose bin differs from the column's known zero
# bin; implicit zeros are reconstructed per leaf as
# `leaf_totals - sum(stored bins)` (exactly the subtraction the dense
# paths already run for larger siblings and EFB default bins,
# ops/split.unbundle_hist).  Compute and histogram input bytes scale
# with nnz instead of F x N — the kernel shape of the sparse GPU
# histogram (arXiv:1706.08359).  Two implementations mirror the dense
# masked pair:
# - `hist_sparse_xla`: per-entry scatter-add (segment-sum), pure XLA —
#   the CPU/test path and the fallback.
# - `hist_sparse_pallas`: entries pre-sorted into FEATURE_GROUP-column
#   windows (ELL-per-window, built once per dataset by
#   `sparse_window_streams`); each grid cell runs the masked kernel's
#   leaf-mask + one-hot matmul over a [Eblk] entry block against the
#   window's flat W*B bin axis, so the MXU contraction idiom carries
#   over unchanged.
# ----------------------------------------------------------------------------

# entry-block length of the sparse pallas kernel: the [Eblk, W*B] f32
# one-hot is the VMEM-dominant transient (512 * 1024 * 4 = 2 MB)
SPARSE_CHUNK = 512


def _slot_of_rows(lid: jax.Array, sl: jax.Array) -> jax.Array:
    """Slot index per row (position of the row's leaf id in `sl`), or K
    for rows whose leaf is not histogrammed this pass — K rows land in
    the scratch slot every scatter below slices off."""
    K = sl.shape[0]
    eq = lid[:, None] == sl[None, :]                     # [N, K]
    return jnp.where(jnp.any(eq, axis=1),
                     jnp.argmax(eq, axis=1).astype(jnp.int32),
                     jnp.int32(K))


def _slot_totals(srow: jax.Array, gh8: jax.Array, K: int) -> jax.Array:
    """[K, 3] per-slot (sum_grad, sum_hess, count) — the zero-bin
    reconstruction anchor, accumulated over ALL rows of each slot.
    Dtype follows gh8: f32 for real-valued grads, int32 for the
    quantized lanes (where the residual must stay an exact integer)."""
    tot = jnp.zeros((K + 1, 3), gh8.dtype)
    return tot.at[srow].add(gh8[:3].T)[:K]


def _apply_zero_bin(hist: jax.Array, tot: jax.Array,
                    zero_bin: jax.Array) -> jax.Array:
    """Reconstruct the implicit-zero bin row of every store column:
    `leaf totals - sum(stored-entry bins)` added at the column's zero
    bin.  hist [K, C, 3, B] (stored entries only), tot [K, 3],
    zero_bin [C] (-1 marks padded columns, which must stay all-zero).
    Exact for counts (integers < 2^24) and within one f32 rounding of
    the dense accumulation for grad/hess — the same property the dense
    paths accept from parent-histogram subtraction.  In the int32
    quantized lanes the subtraction is exact, period."""
    colsum = jnp.sum(hist, axis=3)                       # [K, C, 3]
    resid = jnp.where((zero_bin >= 0)[None, :, None],
                      tot[:, None, :] - colsum,
                      jnp.zeros_like(colsum))
    zb = jnp.clip(zero_bin, 0, hist.shape[3] - 1)
    C = hist.shape[1]
    # advanced-index add: the (arange, zb) pair broadcasts to [C], and
    # with the interleaved slices the advanced axes move first → the
    # update operand is [C, K, 3]
    return hist.at[:, jnp.arange(C), :, zb].add(resid.transpose(1, 0, 2))


def _sparse_quant_ok(input_dtype: str, num_rows: int) -> bool:
    """Trace-time int8 eligibility for the sparse kernels: the same
    int32-exactness bound the dense masked kernel enforces (127·rows
    < 2^31 and per-cell counts < 2^24), keyed on the ROW count — every
    (column, bin) cell accumulates at most one entry per row."""
    if input_dtype != "int8":
        return False
    if num_rows > 16_000_000:
        from .. import log
        # graftlint: allow(retrace-hazard) — deliberate ONE-shot warning at trace time (shape is static, fires once per compile)
        log.warning("histogram_dtype=int8 disabled for this sparse pass: "
                    f"{num_rows} rows exceeds the int32-exactness bound "
                    "(16M rows per device); using float32")
        return False
    return True


@functools.partial(jax.jit, static_argnames=("num_columns_padded",
                                             "num_bins_padded",
                                             "input_dtype"))
def hist_sparse_xla(cols: jax.Array, binsv: jax.Array, zero_bin: jax.Array,
                    lid: jax.Array, gh8: jax.Array, sl: jax.Array, *,
                    num_columns_padded: int,
                    num_bins_padded: int,
                    input_dtype: str = "float32") -> jax.Array:
    """Nonzero-iterating multi-leaf histogram, XLA scatter-add path.

    cols/binsv : [N, R] ELL entries (col >= num_columns_padded marks an
        empty slot); zero_bin [Cp] int32 (-1 = padded column);
    lid [N] int32 leaf ids; gh8 [8, N] f32 (grad·rm, hess·rm, rm, …);
    sl [K] int32 leaf ids to histogram (-1 = empty slot).
    Returns [K, Cp, 3, B] f32 — hist_multileaf_masked's contract over
    the sparse store.

    input_dtype "int8" selects per-pass symmetric gradient quantization
    (_quantize_gh — the dense masked kernel's discipline) with the whole
    accumulation held in INTEGER lanes: int32 scatter-add of the
    quantized entries, int32 slot totals, int32 zero-bin residual, ONE
    dequantizing scale at the end.  That makes the XLA path
    bitwise-identical to the pallas sparse int8 kernel for any
    gradients (both are exact integer sums of the same addends), and
    keeps `totals − Σstored` exact in the integer domain.
    """
    N, R = cols.shape
    K = sl.shape[0]
    Cp, B = num_columns_padded, num_bins_padded
    quant = _sparse_quant_ok(input_dtype, N)
    if quant:
        gh_acc, sg, sh = _quantize_gh(gh8)               # [8, N] int32
    else:
        gh_acc = gh8
    srow = _slot_of_rows(lid, sl)                        # [N]
    tot = _slot_totals(srow, gh_acc, K)
    valid_e = cols < Cp                                  # [N, R]
    # entries of unslotted rows and empty ELL slots both route to the
    # K scratch slot (sliced off); column/bin ids stay in range
    s_e = jnp.where(valid_e, srow[:, None], K).reshape(-1)
    c_e = jnp.minimum(cols, Cp - 1).reshape(-1)
    b_e = jnp.minimum(binsv, B - 1).reshape(-1)
    v3 = jnp.stack([gh_acc[0], gh_acc[1], gh_acc[2]], axis=1)   # [N, 3]
    v_e = jnp.broadcast_to(v3[:, None, :], (N, R, 3)).reshape(-1, 3)
    hist = jnp.zeros((K + 1, Cp, B, 3), gh_acc.dtype)
    hist = hist.at[s_e, c_e, b_e].add(v_e)[:K]           # [K, Cp, B, 3]
    hist = hist.transpose(0, 1, 3, 2)                    # [K, Cp, 3, B]
    hist = _apply_zero_bin(hist, tot, zero_bin)
    if quant:
        scale = jnp.stack([sg, sh, jnp.float32(1.0)])
        hist = hist.astype(jnp.float32) * scale[None, None, :, None]
    return hist


def sparse_window_streams(cols: np.ndarray, binsv: np.ndarray,
                          num_columns: int, *, num_bins_padded: int,
                          window: int = FEATURE_GROUP,
                          chunk: int = SPARSE_CHUNK):
    """Slot-segmented entry streams for the pallas sparse kernel, built
    ONCE per dataset on the host (the store is static; only leaf ids
    and gradients change per pass).

    Entries sort by store column and split into SLOTS of at most
    `chunk` entries — a hot column simply occupies several slots (its
    partial histograms are summed back at unscatter time), so the
    layout is load-balanced by construction: real CTR column
    distributions are power-law, and padding windows to the hottest
    window's length would blow stream memory up by the skew factor
    (~90x at the acceptance shape).  Here memory is
    O(nnz + chunk * nonempty columns) regardless of skew.

    `window` slots share one kernel grid cell; slot s occupies the
    fixed segment [s*chunk, (s+1)*chunk) of its window's stream, so
    every block is one slot's entries — a fully regular
    (windows, window) grid, no scalar prefetch.

    Returns (e_row [nwin, window*chunk] int32 local row ids,
    e_flat [...] int32 flat local bin ids `lane * B + bin` with
    sentinel window*B for padding, e_valid [...] f32 0/1,
    slot_col [nwin*window] int32 store column per slot — sentinel
    num_columns for padding slots; `unscatter_slot_hist` folds the
    kernel output back to columns).
    """
    N, R = cols.shape
    B = num_bins_padded
    W = window
    keep = (cols < num_columns).ravel()
    r_e = np.repeat(np.arange(N, dtype=np.int64), R)[keep]
    c_e = cols.ravel()[keep].astype(np.int64)
    b_e = binsv.ravel()[keep].astype(np.int64)
    order = np.argsort(c_e, kind="stable")
    r_e, c_e, b_e = r_e[order], c_e[order], b_e[order]
    cnt = np.bincount(c_e, minlength=int(num_columns))
    nslot_c = -(-cnt // chunk)                     # 0 for empty columns
    nslots = int(nslot_c.sum())
    nsp = W * max(1, -(-max(nslots, 1) // W))      # pad to a window mult
    slot_col = np.full(nsp, int(num_columns), np.int32)
    slot_col[:nslots] = np.repeat(np.arange(num_columns), nslot_c)
    # entry -> (slot, position): entries are column-sorted, so an
    # entry's slot is its column's first slot + rank-in-column // chunk
    col_off = np.concatenate([[0], np.cumsum(cnt)])
    slot_base = np.concatenate([[0], np.cumsum(nslot_c)])
    rank = np.arange(r_e.size, dtype=np.int64) - col_off[c_e]
    s_e = slot_base[c_e] + rank // chunk
    p_e = rank % chunk
    nwin = nsp // W
    Ew = W * chunk
    e_row = np.zeros((nwin, Ew), np.int32)
    e_flat = np.full((nwin, Ew), W * B, np.int32)
    e_valid = np.zeros((nwin, Ew), np.float32)
    w_e = s_e // W
    pos = (s_e % W) * chunk + p_e
    e_row[w_e, pos] = r_e
    e_flat[w_e, pos] = (s_e % W) * B + b_e
    e_valid[w_e, pos] = 1.0
    return e_row, e_flat, e_valid, slot_col


def unscatter_slot_hist(h_slots: jax.Array, slot_col: jax.Array,
                        num_columns: int) -> jax.Array:
    """[nslots, Mp, B] per-slot partial histograms -> [Cp, Mp, B] by
    summing each column's slots (histograms are additive, so splitting
    a hot column across slots is exact).  Sentinel slots drop."""
    Cp = num_columns
    out = jnp.zeros((Cp + 1,) + h_slots.shape[1:], h_slots.dtype)
    return out.at[slot_col].add(h_slots)[:Cp]


def _hist_kernel_sparse(sl_ref, fb_ref, lid_ref, gh_ref, out_ref, *,
                        WB: int, K: int, input_dtype):
    """One (window, entry-chunk) grid cell of the sparse histogram.

    sl_ref : [Kp, 128] int32 slot leaf ids (replicated across lanes)
    fb_ref : [1, Eblk] int32 flat local bin ids (sentinel WB matches
             no lane)
    lid_ref: [1, Eblk] int32 leaf id of each entry's row
    gh_ref : [1, 8, Eblk] f32 (g·valid, h·valid, valid, pads)
    out_ref: [1, Mp, WB] f32 accumulated across the chunk grid axis

    Identical inner shape to _hist_kernel_masked (leaf masks in VMEM,
    one [Mp, Eblk] @ [Eblk, WB] MXU contraction) — only the one-hot
    axis is the window's flat (local column, bin) product.  The compare
    runs in int32: flat ids reach W*B = 1024, past the int8/bf16 exact
    windows the narrow dense compares rely on.
    """
    from jax.experimental import pallas as pl

    k = pl.program_id(1)

    @pl.when(k == 0)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    lid = lid_ref[0, :]                                  # [Eblk]
    sl = sl_ref[:K, 0:1]                                 # [K, 1]
    m = (lid[None, :] == sl).astype(input_dtype)         # [K, Eblk]
    g = gh_ref[0, 0:1, :].astype(input_dtype)
    h = gh_ref[0, 1:2, :].astype(input_dtype)
    rm = gh_ref[0, 2:3, :].astype(input_dtype)
    vals = jnp.concatenate([m * g, m * h, m * rm], axis=0)   # [3K, Eblk]
    Mp = out_ref.shape[1]
    if Mp > 3 * K:
        vals = jnp.concatenate(
            [vals, jnp.zeros((Mp - 3 * K, vals.shape[1]), input_dtype)],
            axis=0)
    prec = (jax.lax.Precision.HIGHEST if input_dtype == jnp.float32
            else jax.lax.Precision.DEFAULT)
    fb = fb_ref[0, :]
    iota = jax.lax.broadcasted_iota(jnp.int32, (1, WB), 1)
    oh = (fb[:, None] == iota).astype(input_dtype)       # [Eblk, WB]
    out_ref[0, :, :] += jnp.dot(vals, oh,
                                preferred_element_type=jnp.float32,
                                precision=prec)


def _hist_kernel_sparse_q(sl_ref, fb_ref, lid_ref, gh_ref, out_ref, *,
                          WB: int, K: int):
    """Quantized variant of _hist_kernel_sparse: gh_ref carries
    int8-ranged int32 quantized entries, the MXU contraction runs
    int8 x int8 -> int32 and the [1, Mp, WB] output accumulates EXACT
    int32 partial histograms (dequantized once, outside, after the
    slot unscatter and integer zero-bin reconstruction).

    As in _hist_kernel_masked_q, elementwise mask work stays in i32
    (Mosaic has no int8 'arith.muli' on this target) — only the matmul
    OPERANDS are int8, which is where the throughput lives, and the
    i32->i8 truncation is a supported cast (values are int8-ranged by
    construction)."""
    from jax.experimental import pallas as pl

    k = pl.program_id(1)

    @pl.when(k == 0)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    lid = lid_ref[0, :]                                  # [Eblk]
    sl = sl_ref[:K, 0:1]                                 # [K, 1]
    m = (lid[None, :] == sl).astype(jnp.int32)           # [K, Eblk]
    vals32 = jnp.concatenate([m * gh_ref[0, 0:1, :], m * gh_ref[0, 1:2, :],
                              m * gh_ref[0, 2:3, :]], axis=0)   # [3K, Eblk]
    Mp = out_ref.shape[1]
    if Mp > 3 * K:
        vals32 = jnp.concatenate(
            [vals32, jnp.zeros((Mp - 3 * K, vals32.shape[1]), jnp.int32)],
            axis=0)
    vals = vals32.astype(jnp.int8)
    fb = fb_ref[0, :]
    iota = jax.lax.broadcasted_iota(jnp.int32, (1, WB), 1)
    # flat ids reach W*B = 1024, so the compare runs in int32; only the
    # RESULT narrows to int8 (0/1 — exact)
    oh = (fb[:, None] == iota).astype(jnp.int8)          # [Eblk, WB]
    out_ref[0, :, :] += jnp.dot(vals, oh,
                                preferred_element_type=jnp.int32)


@functools.partial(jax.jit, static_argnames=("num_columns_padded",
                                             "num_bins_padded",
                                             "input_dtype", "interpret"))
def hist_sparse_pallas(e_row: jax.Array, e_flat: jax.Array,
                       e_valid: jax.Array, slot_col: jax.Array,
                       zero_bin: jax.Array,
                       lid: jax.Array, gh8: jax.Array, sl: jax.Array, *,
                       num_columns_padded: int, num_bins_padded: int,
                       input_dtype: str = "float32",
                       interpret: bool = False) -> jax.Array:
    """Pallas sparse histogram over slot-segmented entry streams
    (sparse_window_streams).  Per-pass state (leaf ids, gradients) is
    gathered per entry OUTSIDE the kernel — nnz-sized XLA gathers —
    then the grid runs (windows, entry-chunks) and the per-slot
    partial histograms fold back to columns (unscatter_slot_hist).
    Returns [K, Cp, 3, B] f32 with the zero bin reconstructed.

    input_dtype "int8" routes to _hist_kernel_sparse_q: quantized
    entries ride int8 MXU operands into an exact int32 accumulator, the
    slot unscatter and zero-bin residual stay integer, and ONE scale
    dequantizes at the end — bitwise-identical to hist_sparse_xla's
    int8 branch (same integer addends, exact sums in any order)."""
    quant = _sparse_quant_ok(input_dtype, lid.shape[0])
    if not quant:
        input_dtype = _coerce_dtype(input_dtype)
    from jax.experimental import pallas as pl

    nwin, Ew = e_row.shape
    K = sl.shape[0]
    Cp, B = num_columns_padded, num_bins_padded
    W = FEATURE_GROUP
    WB = W * B
    Eblk = min(Ew, SPARSE_CHUNK)
    if quant:
        gh_src, sg, sh = _quantize_gh(gh8)               # [8, N] int32
        acc_dt = jnp.int32
        kern = functools.partial(_hist_kernel_sparse_q, WB=WB, K=K)
    else:
        gh_src = gh8
        acc_dt = jnp.float32
        kern = functools.partial(_hist_kernel_sparse, WB=WB, K=K,
                                 input_dtype=jnp.dtype(input_dtype))
    srow = _slot_of_rows(lid, sl)
    tot = _slot_totals(srow, gh_src, K)
    lid_e = jnp.take(lid, e_row.reshape(-1)).reshape(nwin, Ew)
    ghm = (jnp.take(gh_src[:3], e_row.reshape(-1), axis=1)
           .reshape(3, nwin, Ew).transpose(1, 0, 2))     # [nwin, 3, Ew]
    ghm = ghm * e_valid[:, None, :].astype(acc_dt)
    ghm = jnp.concatenate(
        [ghm, jnp.zeros((nwin, 5, Ew), acc_dt)], axis=1)
    Mp = 8 * ((3 * K + 7) // 8)
    Kp = 8 * ((K + 7) // 8)
    sl2 = jnp.broadcast_to(jnp.pad(sl, (0, Kp - K),
                                   constant_values=-1)[:, None], (Kp, 128))
    grid = (nwin, Ew // Eblk)
    out = pl.pallas_call(
        kern,
        out_shape=jax.ShapeDtypeStruct((nwin, Mp, WB), acc_dt),
        grid=grid,
        in_specs=[
            pl.BlockSpec((Kp, 128), lambda w, k: (0, 0)),
            pl.BlockSpec((1, Eblk), lambda w, k: (w, k)),
            pl.BlockSpec((1, Eblk), lambda w, k: (w, k)),
            pl.BlockSpec((1, 8, Eblk), lambda w, k: (w, 0, k)),
        ],
        out_specs=pl.BlockSpec((1, Mp, WB), lambda w, k: (w, 0, 0)),
        interpret=interpret,
    )(sl2, e_flat, lid_e, ghm)
    # [nwin, Mp, W, B] → [nslots, Mp, B] → columns → [K, Cp, 3, B]
    h_slots = (out.reshape(nwin, Mp, W, B).transpose(0, 2, 1, 3)
               .reshape(nwin * W, Mp, B))
    h = unscatter_slot_hist(h_slots, slot_col, Cp)
    hist = jnp.stack([h[:, :K], h[:, K:2 * K], h[:, 2 * K:3 * K]],
                     axis=2).transpose(1, 0, 2, 3)       # [K, Cp, 3, B]
    hist = _apply_zero_bin(hist, tot, zero_bin)
    if quant:
        scale = jnp.stack([sg, sh, jnp.float32(1.0)])
        hist = hist.astype(jnp.float32) * scale[None, None, :, None]
    return hist


def hist_sparse_multileaf(sp, lid: jax.Array, gh8: jax.Array,
                          sl: jax.Array, *, num_columns_padded: int,
                          num_bins_padded: int, backend: str = "xla",
                          input_dtype: str = "float32",
                          interpret: bool = False) -> jax.Array:
    """Dispatch over the sparse store pytree (cols, binsv, zero_bin,
    e_row, e_flat, e_valid, slot_col): the slot-stream pallas kernel on
    TPU, the scatter-add XLA path elsewhere (stream arrays are then
    empty placeholders).  Same [K, F, 3, B] contract as
    hist_multileaf_masked."""
    cols, binsv, zero_bin, e_row, e_flat, e_valid, slot_col = sp
    if backend == "pallas":
        return hist_sparse_pallas(
            e_row, e_flat, e_valid, slot_col, zero_bin, lid, gh8, sl,
            num_columns_padded=num_columns_padded,
            num_bins_padded=num_bins_padded, input_dtype=input_dtype,
            interpret=interpret)
    return hist_sparse_xla(cols, binsv, zero_bin, lid, gh8, sl,
                           num_columns_padded=num_columns_padded,
                           num_bins_padded=num_bins_padded,
                           input_dtype=input_dtype)


def hist_sparse_gathered(sp, gh8: jax.Array, perm: jax.Array,
                         seg_off: jax.Array, seg_cnt: jax.Array, *,
                         capacity: int, num_columns_padded: int,
                         num_bins_padded: int,
                         input_dtype: str = "float32"):
    """Gathered (ordered) sparse histogram: compact the K leaf-contiguous
    row segments of the device row partition into the static scratch
    (gather_segments — CSR row segments permute exactly like dense
    rows), gather their ELL entries, and histogram only those.  Returns
    ([K, Cp, 3, B] hists, f32 stored entries touched) — the nnz-scaled
    analog of hist_multileaf_gathered, XLA path (the window streams are
    store-order static and cannot be re-sorted per pass)."""
    cols, binsv, zero_bin = sp[0], sp[1], sp[2]
    K = seg_off.shape[0]
    Cp = num_columns_padded
    idx, slot, _ = gather_segments(perm, seg_off, seg_cnt,
                                   capacity=capacity)
    cg = jnp.take(cols, idx, axis=0)                     # [cap, R]
    bg = jnp.take(binsv, idx, axis=0)
    live = (slot >= 0)
    # dead scratch slots: zero vals AND sentinel entries, so neither
    # the totals nor the scatter see them
    cg = jnp.where(live[:, None], cg, Cp)
    ghg = jnp.take(gh8, idx, axis=1) * live[None, :].astype(jnp.float32)
    sl = jax.lax.iota(jnp.int32, K)
    h = hist_sparse_xla(cg, bg, zero_bin, slot, ghg, sl,
                        num_columns_padded=Cp,
                        num_bins_padded=num_bins_padded,
                        input_dtype=input_dtype)
    nnz = jnp.sum((cg < Cp).astype(jnp.float32))
    return h, nnz


def histogram_full_masked(bins: jax.Array, grad: jax.Array, hess: jax.Array,
                          mask: jax.Array, *, num_bins_padded: int,
                          input_dtype: str = "float32") -> jax.Array:
    """Full-scan masked histogram over ALL rows (no gather) — used by
    the fused leaf-wise learner, whose one-leaf-at-a-time passes keep
    mask construction cheaper than maintaining a row partition.

    bins: [F, N] (no sentinel), mask: [N] float32 0/1 row weights.
    Returns [F, 3, B] float32.
    """
    vals = jnp.stack([grad * mask, hess * mask, mask])   # [3, N]
    return hist_xla(bins.T.astype(jnp.int32), vals,
                    num_bins_padded=num_bins_padded, input_dtype=input_dtype)


def histogram_full_sparse(cols: jax.Array, binsv: jax.Array,
                          zero_bin: jax.Array, grad: jax.Array,
                          hess: jax.Array, mask: jax.Array, *,
                          num_columns_padded: int, num_bins_padded: int,
                          input_dtype: str = "float32") -> jax.Array:
    """histogram_full_masked's contract over a per-shard ELL window —
    the fused (feature-sharded / voting) learners' sparse feed.

    cols/binsv: [N, R] ELL entries in the shard's LOCAL column space
    (col >= num_columns_padded marks an empty slot); zero_bin [Cp] int32
    (-1 = padded column); grad/hess [N] f32; mask [N] f32 0/1 row
    weights.  Returns [Cp, 3, B] f32 — masked rows contribute zero to
    both the stored entries and the totals, so the zero-bin residual is
    exact for any mask (the K=1 specialization of hist_sparse_xla).

    int8 coerces like the dense fused feed does (_coerce_dtype): the
    fused learners' quantized story is the rounds learner's — keeping
    both feeds f32 preserves the sparse-vs-dense dyadic-bitwise parity
    contract per learner.
    """
    N = grad.shape[0]
    gh8 = jnp.concatenate(
        [jnp.stack([grad * mask, hess * mask, mask]),
         jnp.zeros((5, N), jnp.float32)], axis=0)
    lid = jnp.zeros((N,), jnp.int32)
    sl = jnp.zeros((1,), jnp.int32)
    h = hist_sparse_xla(cols, binsv, zero_bin, lid, gh8, sl,
                        num_columns_padded=num_columns_padded,
                        num_bins_padded=num_bins_padded,
                        input_dtype=_coerce_dtype(input_dtype))
    return h[0]
