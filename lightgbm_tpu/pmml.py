"""PMML export (reference pmml/pmml.py: model text → PMML 4.2).

Re-designed from the model structures instead of re-parsing text: each
tree becomes a `<TreeModel>` segment of a summing `<MiningModel>`.
"""
from __future__ import annotations

from typing import List, Optional
from xml.sax.saxutils import quoteattr


def _tree_nodes(node: dict, feature_names: List[str], lines: List[str],
                indent: int, predicate: Optional[str]) -> None:
    pad = "  " * indent
    pred = predicate if predicate is not None else "<True/>"
    if "leaf_index" in node:
        lines.append(f'{pad}<Node id="leaf{node["leaf_index"]}" '
                     f'score="{node["leaf_value"]:.17g}">')
        lines.append(f"{pad}  {pred}")
        lines.append(f"{pad}</Node>")
        return
    feat = quoteattr(feature_names[node["split_feature"]])
    thr = f'{node["threshold"]:.17g}'
    cat = node.get("decision_type") == "is"   # reference JSON type name
    op_l = "equal" if cat else "lessOrEqual"
    op_r = "notEqual" if cat else "greaterThan"
    lines.append(f'{pad}<Node id="split{node["split_index"]}" '
                 f'score="{node.get("internal_value", 0.0):.17g}">')
    lines.append(f"{pad}  {pred}")
    _tree_nodes(node["left_child"], feature_names, lines, indent + 1,
                f'<SimplePredicate field={feat} operator="{op_l}" '
                f'value="{thr}"/>')
    _tree_nodes(node["right_child"], feature_names, lines, indent + 1,
                f'<SimplePredicate field={feat} operator="{op_r}" '
                f'value="{thr}"/>')
    lines.append(f"{pad}</Node>")


def model_to_pmml(booster, model_name: str = "lightgbm_tpu") -> str:
    """PMML document string for a trained Booster / GBDT."""
    gbdt = getattr(booster, "_gbdt", booster)
    model = gbdt.to_json()
    feature_names = list(model["feature_names"])
    lines = [
        '<?xml version="1.0" encoding="UTF-8"?>',
        '<PMML version="4.2" xmlns="http://www.dmg.org/PMML-4_2">',
        f'  <Header description="{model_name}"/>',
        "  <DataDictionary>",
    ]
    for nm in feature_names:
        lines.append(f'    <DataField name={quoteattr(nm)} '
                     'optype="continuous" dataType="double"/>')
    lines.append('    <DataField name="prediction" optype="continuous" '
                 'dataType="double"/>')
    lines.append("  </DataDictionary>")
    lines.append('  <MiningModel functionName="regression" '
                 f'modelName={quoteattr(model_name)}>')
    lines.append("    <MiningSchema>")
    for nm in feature_names:
        lines.append(f'      <MiningField name={quoteattr(nm)}/>')
    lines.append('      <MiningField name="prediction" '
                 'usageType="predicted"/>')
    lines.append("    </MiningSchema>")
    lines.append('    <Segmentation multipleModelMethod="sum">')
    for i, tree in enumerate(model["tree_info"]):
        lines.append(f'      <Segment id="{i + 1}">')
        lines.append("        <True/>")
        lines.append('        <TreeModel functionName="regression" '
                     'splitCharacteristic="binarySplit">')
        lines.append("          <MiningSchema>")
        for nm in feature_names:
            lines.append(f'            <MiningField name={quoteattr(nm)}/>')
        lines.append("          </MiningSchema>")
        _tree_nodes(tree["tree_structure"], feature_names, lines, 5, None)
        lines.append("        </TreeModel>")
        lines.append("      </Segment>")
    lines.append("    </Segmentation>")
    lines.append("  </MiningModel>")
    lines.append("</PMML>")
    return "\n".join(lines)


def save_pmml(booster, filename: str, model_name: str = "lightgbm_tpu"
              ) -> None:
    with open(filename, "w") as f:
        f.write(model_to_pmml(booster, model_name))
